"""Plan optimizer: stats estimation, structural properties, rule passes.

Reference parity: ``PlanOptimizers``' rule pipeline with
``StatsCalculator``/``CostCalculator`` inputs (SURVEY.md §2.1
"Optimizer"). Round 1 carries the load-bearing subset:

- ``estimate_rows``: cardinality estimates from connector stats with
  classic selectivity constants (drives greedy join ordering and the
  static capacity buckets XLA needs)
- ``unique_key_sets``: key-uniqueness inference (drives the PK-FK
  ``build_unique`` fast path in the join kernel)
- ``prune_columns``: column pruning down to scans (the reference's
  PruneUnreferencedOutputs), which on this engine also shrinks
  host->device staging traffic
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set

from presto_tpu import expr as E
from presto_tpu.plan import nodes as N

#: fallback when a predicate's shape/stats give no better signal
FILTER_SELECTIVITY = 0.33


def _column_stats(node: N.PlanNode, col: str, catalogs):
    """ColumnStats for ``col`` seen through filters/projections down to
    the scan (identity renames only), or None."""
    if isinstance(node, N.TableScanNode):
        stats = (
            catalogs.get(node.handle.catalog)
            .metadata()
            .get_table_stats(node.handle)
        )
        return (stats.columns or {}).get(col)
    if isinstance(node, N.FilterNode):
        return _column_stats(node.source, col, catalogs)
    if isinstance(node, N.ProjectNode):
        for out_name, e in node.projections:
            if out_name == col and isinstance(e, E.ColumnRef):
                return _column_stats(node.source, e.name, catalogs)
        return None
    if isinstance(node, N.JoinNode):
        # a join carries probe columns plus build payload under their
        # own names — thread through to whichever side owns the column
        # (the bushy-rescue pseudo-relation is such a tree; without
        # this its NDVs vanish and output caps explode)
        if col in node.left.output_schema():
            return _column_stats(node.left, col, catalogs)
        if col in node.right.output_schema():
            return _column_stats(node.right, col, catalogs)
        return None
    if isinstance(node, N.AggregationNode):
        # group keys carry source values through (value RANGE stats
        # stay valid; NDV can only shrink, which the consumers treat
        # as an upper bound) — the q78 CTE shape packs its 3-key
        # outer join on these
        for name, e in node.group_keys:
            if name == col and isinstance(e, E.ColumnRef):
                return _column_stats(node.source, e.name, catalogs)
        return None
    if isinstance(node, N.OutputNode):
        src = dict(node.columns).get(col)
        if src is not None:
            return _column_stats(node.source, src, catalogs)
        return None
    if isinstance(node, (N.SortNode, N.LimitNode, N.DistinctNode)):
        return _column_stats(node.source, col, catalogs)
    return None


def _conjuncts_of(e: E.Expr) -> List[E.Expr]:
    if isinstance(e, E.And):
        out: List[E.Expr] = []
        for c in e.terms:
            out.extend(_conjuncts_of(c))
        return out
    return [e]


def _one_selectivity(e: E.Expr, source: N.PlanNode, catalogs) -> float:
    """Selectivity of a single conjunct (reference: StatsCalculator's
    filter estimation — equality via 1/NDV, ranges via the value span,
    IN via |list|/NDV; shape defaults otherwise)."""
    if isinstance(e, E.Compare) and isinstance(e.left, E.ColumnRef):
        cs = _column_stats(source, e.left.name, catalogs)
        if isinstance(e.right, E.Literal) and e.right.value is not None:
            if e.op == "=" and cs and cs.distinct_count:
                return 1.0 / max(cs.distinct_count, 1.0)
            if e.op in ("<", "<=", ">", ">=") and cs and (
                cs.min_value is not None
                and cs.max_value is not None
                and cs.max_value > cs.min_value
                and isinstance(e.right.value, (int, float))
            ):
                span = cs.max_value - cs.min_value
                v = float(e.right.value)
                frac = (v - cs.min_value) / span
                if e.op in (">", ">="):
                    frac = 1.0 - frac
                return min(max(frac, 0.0), 1.0)
            if e.op == "<>":
                return 0.9
        return 0.33 if e.op != "=" else 0.1
    if isinstance(e, E.Between) and isinstance(e.arg, E.ColumnRef):
        cs = _column_stats(source, e.arg.name, catalogs)
        if (
            cs
            and cs.min_value is not None
            and cs.max_value is not None
            and cs.max_value > cs.min_value
            and isinstance(getattr(e.low, "value", None), (int, float))
            and isinstance(getattr(e.high, "value", None), (int, float))
        ):
            span = cs.max_value - cs.min_value
            frac = (float(e.high.value) - float(e.low.value)) / span
            frac = min(max(frac, 0.0), 1.0)
            return (1.0 - frac) if e.negate else frac
        return 0.25
    if isinstance(e, E.InList):
        cs = (
            _column_stats(source, e.arg.name, catalogs)
            if isinstance(e.arg, E.ColumnRef)
            else None
        )
        if cs and cs.distinct_count:
            frac = min(len(e.values) / max(cs.distinct_count, 1.0), 1.0)
            return (1.0 - frac) if e.negate else frac
        return 0.2
    if isinstance(e, E.Or):
        s = 0.0
        for t in e.terms:
            s += _one_selectivity(t, source, catalogs)
        return min(s, 1.0)
    if isinstance(e, E.Not):
        return 1.0 - _one_selectivity(e.arg, source, catalogs)
    return FILTER_SELECTIVITY


def predicate_selectivity(
    pred: E.Expr, source: N.PlanNode, catalogs
) -> float:
    s = 1.0
    for c in _conjuncts_of(pred):
        s *= _one_selectivity(c, source, catalogs)
    return max(s, 1e-6)


def estimate_rows(node: N.PlanNode, catalogs) -> float:
    """Cardinality estimate for ``node``. Consults history-based
    statistics FIRST (plan/history.py — observed actuals keyed by the
    node's canonical sub-fingerprint, active only when the runner
    installed a store under session ``enable_history_stats``), then
    connector stats / heuristics. With no active store the lookup is
    one thread-local read and the math is bit-exact pre-history."""
    from presto_tpu.plan import history

    got = history.lookup_rows(node)
    if got is not None:
        return max(float(got), 1.0)
    rows = _estimate_rows_classic(node, catalogs)
    # adaptive execution: an active capture scope remembers the classic
    # estimate a history MISS fell back to — the base the replan seam's
    # divergence test compares the first learned cardinality against
    # (no-op outside a capture scope)
    history.note_estimate(node, rows)
    return rows


def estimate_rows_with_source(
    node: N.PlanNode, catalogs, stats_memo: Optional[dict] = None
):
    """-> (rows, provenance) where provenance is ``history`` (learned
    from a prior execution of this canonical shape), ``stats`` (every
    scan under the node has connector row counts), or ``heuristic``.
    EXPLAIN renders the provenance beside each estimate — render-time
    only; the hot planning path uses :func:`estimate_rows`, which
    skips the provenance walk. Callers estimating a whole tree pass
    one ``stats_memo`` dict so each table's connector stats are
    fetched once, not once per ancestor node."""
    from presto_tpu.plan import history

    got = history.lookup_rows(node)
    if got is not None:
        return max(float(got), 1.0), "history"
    rows = _estimate_rows_classic(node, catalogs)
    return rows, (
        "stats"
        if _subtree_has_stats(node, catalogs, stats_memo)
        else "heuristic"
    )


def _subtree_has_stats(
    node: N.PlanNode, catalogs, memo: Optional[dict] = None
) -> bool:
    """Coarse provenance check: every scan under ``node`` reports a
    connector row count (the estimate is grounded in stats, not in
    shape defaults). ``memo`` caches per-table verdicts across calls
    — a connector whose get_table_stats does real I/O must not pay
    depth-many fetches per scan when a whole tree is estimated."""
    scans = [
        n for n in N.walk(node) if isinstance(n, N.TableScanNode)
    ]
    if not scans:
        return False
    for s in scans:
        key = (s.handle.catalog, s.handle.schema, s.handle.table)
        ok = memo.get(key) if memo is not None else None
        if ok is None:
            try:
                st = (
                    catalogs.get(s.handle.catalog)
                    .metadata()
                    .get_table_stats(s.handle)
                )
                ok = bool(st.row_count)
            except Exception:
                ok = False
            if memo is not None:
                memo[key] = ok
        if not ok:
            return False
    return True


def _estimate_rows_classic(node: N.PlanNode, catalogs) -> float:
    if isinstance(node, N.TableScanNode):
        stats = catalogs.get(node.handle.catalog).metadata().get_table_stats(
            node.handle
        )
        return stats.row_count or 1000.0
    if isinstance(node, N.ValuesNode):
        return 1.0
    if isinstance(node, N.FilterNode):
        sel = predicate_selectivity(node.predicate, node.source, catalogs)
        return max(estimate_rows(node.source, catalogs) * sel, 1.0)
    if isinstance(node, (N.ProjectNode, N.WindowNode, N.OutputNode)):
        return estimate_rows(node.source, catalogs)
    if isinstance(node, N.AggregationNode):
        src = estimate_rows(node.source, catalogs)
        if not node.group_keys:
            return 1.0
        # groups = product of key NDVs when stats know them (capped by
        # the input rows), else the classic 10% guess
        ndv = 1.0
        known = True
        for _, e in node.group_keys:
            if isinstance(e, E.ColumnRef):
                cs = _column_stats(node.source, e.name, catalogs)
                if cs and cs.distinct_count:
                    ndv *= cs.distinct_count
                    continue
            known = False
            break
        groups = ndv if known else src * 0.1
        return max(min(groups, src, float(node.max_groups)), 1.0)
    if isinstance(node, N.DistinctNode):
        return max(estimate_rows(node.source, catalogs) * 0.5, 1.0)
    if isinstance(node, N.SortNode):
        src = estimate_rows(node.source, catalogs)
        return min(src, node.limit) if node.limit else src
    if isinstance(node, N.LimitNode):
        return min(estimate_rows(node.source, catalogs), node.count)
    if isinstance(node, N.UnnestNode):
        if node.array_column is not None:
            return estimate_rows(node.source, catalogs) * 4.0
        return estimate_rows(node.source, catalogs) * len(node.elements)
    if isinstance(node, N.UnionAllNode):
        return sum(estimate_rows(s, catalogs) for s in node.sources)
    if isinstance(node, N.JoinNode):
        probe = estimate_rows(node.left, catalogs)
        if node.join_type in ("semi", "anti"):
            return max(probe * 0.5, 1.0)
        if node.build_unique:
            return probe
        build = estimate_rows(node.right, catalogs)
        return max(probe, build)
    # unknown node (e.g. planner-internal): be conservative
    total = 1.0
    for c in node.children():
        total *= max(estimate_rows(c, catalogs), 1.0)
    return total


def unique_key_sets(node: N.PlanNode, catalogs) -> List[FrozenSet[str]]:
    """Column sets guaranteed unique per row of ``node`` (PK inference)."""
    if isinstance(node, N.TableScanNode):
        stats = catalogs.get(node.handle.catalog).metadata().get_table_stats(
            node.handle
        )
        out = []
        # NDV stats are ESTIMATES (FK columns report min(ref, n), which
        # equals the row count whenever the referenced table is bigger
        # — e.g. 1000 customers drawing from 5600 demographics rows
        # have ~917 DISTINCT values while stats claim 1000). Inferring
        # uniqueness from them made join kernels keep ONE match per
        # probe row and silently drop the rest; only declared primary
        # keys prove uniqueness.
        if stats.primary_key and all(
            c in node.columns for c in stats.primary_key
        ):
            pk = frozenset(stats.primary_key)
            if pk not in out:
                out.append(pk)
        return out
    if isinstance(node, N.FilterNode):
        return unique_key_sets(node.source, catalogs)
    if isinstance(node, (N.SortNode, N.LimitNode, N.WindowNode)):
        return unique_key_sets(node.source, catalogs)
    if isinstance(node, N.ProjectNode):
        # identity projections propagate uniqueness through renames
        rename: Dict[str, str] = {}
        for out_name, e in node.projections:
            if isinstance(e, E.ColumnRef):
                rename.setdefault(e.name, out_name)
        child = unique_key_sets(node.source, catalogs)
        out = []
        for ks in child:
            if all(k in rename for k in ks):
                out.append(frozenset(rename[k] for k in ks))
        return out
    if isinstance(node, N.OutputNode):
        child = unique_key_sets(node.source, catalogs)
        rename = {src: out for out, src in node.columns}
        out = []
        for ks in child:
            if all(k in rename for k in ks):
                out.append(frozenset(rename[k] for k in ks))
        return out
    if isinstance(node, N.AggregationNode):
        if node.group_keys:
            return [frozenset(n for n, _ in node.group_keys)]
        return [frozenset()]  # single row
    if isinstance(node, N.DistinctNode):
        return [frozenset(node.output_schema())]
    if isinstance(node, N.JoinNode):
        if node.join_type in ("semi", "anti"):
            return unique_key_sets(node.left, catalogs)
        if node.build_unique:
            return unique_key_sets(node.left, catalogs)
        return []
    return []


def is_build_unique(
    build: N.PlanNode, build_keys, catalogs
) -> bool:
    keys = set(build_keys)
    for ks in unique_key_sets(build, catalogs):
        if ks <= keys:
            return True
    return False


# ------------------------------------------------------------ column pruning


def _expr_columns(e: E.Expr, out: Set[str]) -> None:
    if isinstance(e, E.ColumnRef):
        out.add(e.name)
    for c in e.children():
        _expr_columns(c, out)


def normalize_interior_outputs(
    node: N.PlanNode, is_root: bool = True
) -> N.PlanNode:
    """Rewrite non-root OutputNodes (subquery relations keep one from
    plan_select) into plain projections: an interior Output is just a
    column select/rename, and leaving it blocks the fragmenter's
    distributable-subtree detection and the fragment-weight model."""
    node = N.map_children(
        node, lambda c: normalize_interior_outputs(c, is_root=False)
    )
    if not is_root and isinstance(node, N.OutputNode):
        src_schema = node.source.output_schema()
        return N.ProjectNode(
            source=node.source,
            projections=tuple(
                (out, E.ColumnRef(col, src_schema[col]))
                for out, col in node.columns
            ),
        )
    return node


def prune_columns(node: N.PlanNode, required: Optional[Set[str]] = None):
    """Drop unused columns, pushing requirements down to scans
    (reference: PruneUnreferencedOutputs / pushdown of column sets into
    ConnectorPageSource — SURVEY.md §2.2 pushdown surface)."""
    if required is None:
        node = normalize_interior_outputs(node)
    if isinstance(node, N.OutputNode):
        need = {src for _, src in node.columns}
        return dataclasses.replace(
            node, source=prune_columns(node.source, need)
        )
    if required is None:
        required = set(node.output_schema())

    if isinstance(node, N.TableScanNode):
        cols = tuple(c for c in node.columns if c in required) or node.columns[:1]
        return dataclasses.replace(
            node,
            columns=cols,
            schema=tuple((n, t) for n, t in node.schema if n in cols),
        )
    if isinstance(node, N.FilterNode):
        need = set(required)
        _expr_columns(node.predicate, need)
        return dataclasses.replace(
            node, source=prune_columns(node.source, need)
        )
    if isinstance(node, N.ProjectNode):
        # keep at least one projection (same fallback as scans): a
        # zero-column page has capacity 0 and loses its row count
        # (count(*) over a fully-pruned union/subquery)
        projs = tuple(
            (n, e) for n, e in node.projections if n in required
        ) or node.projections[:1]
        need: Set[str] = set()
        for _, e in projs:
            _expr_columns(e, need)
        return dataclasses.replace(
            node,
            projections=projs,
            source=prune_columns(node.source, need),
        )
    if isinstance(node, N.AggregationNode):
        need: Set[str] = set()
        for _, e in node.group_keys:
            _expr_columns(e, need)
        for a in node.aggs:
            if a.arg is not None:
                _expr_columns(a.arg, need)
            if a.arg2 is not None:  # min_by/max_by ordering argument
                _expr_columns(a.arg2, need)
        return dataclasses.replace(
            node, source=prune_columns(node.source, need)
        )
    if isinstance(node, N.JoinNode):
        rename = dict(node.payload_rename)
        lneed = {c for c in required if c in node.left.output_schema()}
        lneed.update(node.left_keys)
        inv = {rename.get(c, c): c for c in node.payload}
        rneed = {
            inv[c] for c in required if c in inv
        }
        rneed.update(node.right_keys)
        if node.residual is not None:
            resid_cols: Set[str] = set()
            _expr_columns(node.residual, resid_cols)
            lsch = node.left.output_schema()
            for c in resid_cols:
                if c in lsch:
                    lneed.add(c)
                elif c in inv:
                    rneed.add(inv[c])
                else:
                    rneed.add(c)
        payload = tuple(
            c for c in node.payload
            if rename.get(c, c) in required or c in rneed
        )
        return dataclasses.replace(
            node,
            left=prune_columns(node.left, lneed),
            right=prune_columns(node.right, rneed),
            payload=payload,
        )
    if isinstance(node, N.SortNode):
        need = set(required)
        for k in node.keys:
            _expr_columns(k.expr, need)
        return dataclasses.replace(
            node, source=prune_columns(node.source, need)
        )
    if isinstance(node, N.LimitNode):
        return dataclasses.replace(
            node, source=prune_columns(node.source, set(required))
        )
    if isinstance(node, N.DistinctNode):
        return dataclasses.replace(
            node, source=prune_columns(node.source, set(node.source.output_schema()))
        )
    if isinstance(node, N.WindowNode):
        need = set(required) - {c.out_name for c in node.calls}
        for e in node.partition_by:
            _expr_columns(e, need)
        for k in node.order_by:
            _expr_columns(k.expr, need)
        for c in node.calls:
            if c.arg is not None:
                _expr_columns(c.arg, need)
        # window preserves all source columns; required source cols only
        return dataclasses.replace(
            node, source=prune_columns(node.source, need)
        )
    if isinstance(node, N.UnnestNode):
        need = set(required) - {node.out_name, node.ordinality_name}
        for e in node.elements:
            _expr_columns(e, need)
        if node.array_column is not None:
            need.add(node.array_column)
        return dataclasses.replace(
            node, source=prune_columns(node.source, need)
        )
    if isinstance(node, N.UnionAllNode):
        # sources share the same output names by construction
        return dataclasses.replace(
            node,
            sources=tuple(
                prune_columns(s, set(required)) for s in node.sources
            ),
        )
    if isinstance(node, N.ValuesNode):
        return node
    return node


def push_scan_constraints(node: N.PlanNode) -> N.PlanNode:
    """TupleDomain-lite pushdown (reference: PickTableLayout pushing
    TupleDomain into the split manager): collect ``col = literal`` and
    ``col IN (literals)`` conjuncts from FilterNodes sitting directly
    above a scan (through other filters) and annotate the scan's
    ``constraint``. The filter stays in place — the constraint only
    lets connectors skip splits (hive partition pruning); ignoring it
    is always correct."""
    if isinstance(node, N.FilterNode):
        chain = [node]
        src = node.source
        while isinstance(src, N.FilterNode):
            chain.append(src)
            src = src.source
        if isinstance(src, N.TableScanNode):
            domains: Dict[str, tuple] = {}
            for f in chain:
                for c in _conjuncts_of(f.predicate):
                    col_vals = _equality_domain(c)
                    if col_vals is None:
                        continue
                    col, vals = col_vals
                    if col in domains:
                        vals = tuple(
                            v for v in vals if v in set(domains[col])
                        )
                    domains[col] = vals
            if domains:
                scan = dataclasses.replace(
                    src,
                    constraint=tuple(sorted(domains.items())),
                )
                rebuilt: N.PlanNode = scan
                for f in reversed(chain):
                    rebuilt = dataclasses.replace(f, source=rebuilt)
                return rebuilt
        return dataclasses.replace(
            node, source=push_scan_constraints(node.source)
        )
    if not node.children():
        return node
    return N.map_children(node, push_scan_constraints)


def _equality_domain(e: E.Expr):
    """ColumnRef = Literal  /  ColumnRef IN (literals)  ->
    (column, values) or None. Only integer- and string-typed literals
    become domains: a decimal literal's stored value is UNSCALED (2024.0
    -> 20240), so passing it through would prune wrongly — those
    predicates simply stay unpruned filters."""
    if (
        isinstance(e, E.Compare)
        and e.op == "="
        and isinstance(e.left, E.ColumnRef)
        and _domain_value(e.right) is not None
    ):
        return e.left.name, (_domain_value(e.right),)
    if (
        isinstance(e, E.Compare)
        and e.op == "="
        and isinstance(e.right, E.ColumnRef)
        and _domain_value(e.left) is not None
    ):
        return e.right.name, (_domain_value(e.left),)
    if (
        isinstance(e, E.InList)
        and not e.negate
        and isinstance(e.arg, E.ColumnRef)
        and all(_domain_value(v) is not None for v in e.values)
    ):
        return e.arg.name, tuple(_domain_value(v) for v in e.values)
    return None


def _domain_value(lit: E.Expr):
    """Literal -> the value a connector compares partition keys
    against, or None when the literal cannot safely become a domain
    (non-literal, NULL, or a scaled-decimal whose stored value is the
    unscaled integer)."""
    if not isinstance(lit, E.Literal) or lit.value is None:
        return None
    if lit.dtype.is_string:
        return str(lit.value)
    if lit.dtype.is_integer:
        return lit.value
    return None
