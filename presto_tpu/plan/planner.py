"""Analyzer + logical planner: parse tree -> typed PlanNode tree.

Reference parity: ``StatementAnalyzer``/``ExpressionAnalyzer`` (name and
type resolution, SURVEY.md §2.1 "Analyzer") fused with ``LogicalPlanner``
/ ``RelationPlanner`` / ``QueryPlanner`` (SURVEY.md §2.1 "Logical
planner"), including the subquery rewrites the reference does in its
optimizer (ApplyNode decorrelation):

- IN (subquery)      -> semi join        (NOT IN -> NULL-AWARE anti
                        join: two bound count params + a probe-side
                        pre-filter give exact three-valued NOT IN
                        semantics — see _null_aware_prefilter)
- EXISTS             -> semi/anti join on equality correlation conjuncts
- scalar subquery    -> uncorrelated: Param bound by the executor;
                        correlated: GROUP BY correlation keys + join
                        (the classic Q2/Q17 decorrelation)
- count(DISTINCT x)  -> two-level aggregation (distinct then count)

Join planning collects relations + equi-conjuncts into a join graph and
orders greedily by connector stats (largest relation stays the probe
backbone, smallest connected relation builds next) — the round-1 stand-in
for the reference's cost-based ReorderJoins + AddExchanges distribution
choice (SURVEY.md §2.1 "Optimizer").
"""

from __future__ import annotations

import dataclasses
import datetime
from typing import Dict, List, Optional, Sequence, Set, Tuple

from presto_tpu import types as T
from presto_tpu import expr as E
from presto_tpu import functions
from presto_tpu.connectors.spi import Connector, TableHandle
from presto_tpu.exec.staging import bucket_capacity
from presto_tpu.ops.aggregation import AggCall
from presto_tpu.ops.sort import SortKey
from presto_tpu.ops.window import WindowCall
from presto_tpu.plan import nodes as N
from presto_tpu.session import Session
from presto_tpu.sql import ast


class PlanningError(ValueError):
    pass


@dataclasses.dataclass
class Plan:
    """Root plan + scalar-subquery subplans to bind (param_id -> plan).

    A plan served from the statement-level plan cache
    (plan/canonical.py) additionally carries ``bound_values`` — the
    current execution's literal values by RuntimeParam ordinal — and
    ``preoptimized`` marks a cached root that already went through
    prune_columns + push_scan_constraints (both are value-independent
    over a canonical root, so re-running them per execution would be
    planning work the cache exists to skip)."""

    root: N.PlanNode
    params: List[Tuple[int, "Plan"]]
    output_names: Tuple[str, ...]
    bound_values: Optional[Dict[int, "E.Literal"]] = None
    preoptimized: bool = False


_AMBIGUOUS = object()


class Scope:
    """Name resolution environment (reference: analyzer Scope).

    ``columns`` maps *internal* (plan) column names to types; internal
    names are globally unique within a query (self-joined tables get
    renamed via projections). ``qualifiers`` maps relation alias ->
    {visible name -> internal name}. Unqualified lookup goes through the
    visible map, where duplicated visible names are poisoned as
    ambiguous (resolvable only via their alias, per SQL)."""

    def __init__(
        self,
        columns: Dict[str, T.DataType],
        qualifiers: Optional[Dict[str, Dict[str, str]]] = None,
        parent: Optional["Scope"] = None,
    ):
        self.columns = dict(columns)
        self.qualifiers = {
            k: dict(v) for k, v in (qualifiers or {}).items()
        }
        self.parent = parent
        self.visible: Dict[str, object] = {}
        if self.qualifiers:
            for m in self.qualifiers.values():
                for vis, internal in m.items():
                    if vis in self.visible and self.visible[vis] != internal:
                        self.visible[vis] = _AMBIGUOUS
                    else:
                        self.visible[vis] = internal
            for c in self.columns:  # columns not owned by any alias
                if not any(c in m.values() for m in self.qualifiers.values()):
                    self.visible.setdefault(c, c)
        else:
            self.visible = {c: c for c in self.columns}

    def merge(self, other: "Scope") -> "Scope":
        clash = set(self.columns) & set(other.columns)
        if clash:
            raise PlanningError(
                f"internal column clash (planner bug): {sorted(clash)}"
            )
        cols = {**self.columns, **other.columns}
        quals = {k: dict(v) for k, v in self.qualifiers.items()}
        for q, m in other.qualifiers.items():
            if q in quals:
                raise PlanningError(f"duplicate relation alias: {q}")
            quals[q] = dict(m)
        s = Scope(cols, quals, self.parent)
        return s

    def resolve(self, parts: Tuple[str, ...]):
        """-> (internal name, dtype, is_outer)."""
        if len(parts) == 1:
            name = parts[0]
            got = self.visible.get(name)
            if got is _AMBIGUOUS:
                raise PlanningError(f"ambiguous column name: {name}")
            if got is not None:
                return got, self.columns[got], False
        elif len(parts) == 2:
            qual, name = parts
            m = self.qualifiers.get(qual)
            if m is not None and name in m:
                internal = m[name]
                return internal, self.columns[internal], False
        if self.parent is not None:
            n, t, _ = self.parent.resolve(parts)
            return n, t, True
        raise PlanningError(f"column not found: {'.'.join(parts)}")


# Aggregate and window builtins resolve through the declarative
# registry (presto_tpu.functions.AGGREGATE / .WINDOW) — the reference's
# FunctionAndTypeManager seam. Adding an aggregate or window function
# touches only functions.py (and, for new KERNEL accumulators, the
# ops kernel); the planner has no builtin name lists of its own.


def plan_statement(
    stmt: ast.Node, catalogs, session: Session
) -> Plan:
    return _Planner(catalogs, session).plan(stmt)


class _Planner:
    def __init__(self, catalogs, session: Session):
        self.catalogs = catalogs
        self.session = session
        self.ctes: Dict[str, ast.Select] = {}
        self._param_counter = [0]
        self.params: List[Tuple[int, Plan]] = []
        self._name_counter = [0]

    def _fresh(self, prefix: str) -> str:
        self._name_counter[0] += 1
        return f"${prefix}_{self._name_counter[0]}"

    # ------------------------------------------------------------ top level

    def plan(self, stmt: ast.Node) -> Plan:
        if not isinstance(stmt, ast.Select):
            raise PlanningError(f"cannot plan {type(stmt).__name__}")
        node, scope, names = self.plan_select(stmt, outer=None)
        return Plan(root=node, params=self.params, output_names=names)

    # ---------------------------------------------------------- SELECT core

    def plan_select(
        self, sel: ast.Select, outer: Optional[Scope]
    ) -> Tuple[N.PlanNode, Scope, Tuple[str, ...]]:
        from presto_tpu.sql.grouping_sets import (
            desugar_select,
            has_grouping_sets,
        )

        if has_grouping_sets(sel):
            try:
                sel = desugar_select(sel)
            except ValueError as e:
                raise PlanningError(str(e))
        saved_ctes = dict(self.ctes)
        for name, q in sel.ctes:
            self.ctes[name] = q
        try:
            return self._plan_select_body(sel, outer)
        finally:
            self.ctes = saved_ctes

    def _plan_select_body(self, sel: ast.Select, outer):
        # 1. FROM -> relations + equi-edge pool; LEFT joins defer so
        # the probe spine's pool sees WHERE equi-edges first
        pending_on: List[ast.Node] = []
        deferred: List[Tuple[ast.Node, Optional[ast.Node]]] = []
        node, scope = self._plan_from(
            sel.from_, outer,
            pending_out=pending_on,
            deferred_out=deferred,
        )

        # 2. WHERE + JOIN..ON conjuncts in ONE application, so the
        # join pool sees the full equi-edge set at once. With deferred
        # LEFT joins, conjuncts that resolve against the probe scope
        # push down (preserved-side pushdown); the rest — anything
        # touching a deferred build column (`p_promo_sk is null`) —
        # apply after those joins attach.
        conjs = list(pending_on)
        if sel.where is not None:
            conjs.extend(_split_conjuncts(sel.where))
        if deferred:
            probe_conjs, post_conjs = [], []
            for c in conjs:
                # subquery-bearing conjuncts go post unconditionally:
                # _resolvable_in skips nested Select bodies, so a
                # subquery correlated to a deferred build column would
                # otherwise misclassify as probe-pushable; applying
                # after the joins is always the plain WHERE semantics
                (
                    probe_conjs
                    if not _contains_select(c)
                    and self._resolvable_in(c, scope)
                    else post_conjs
                ).append(c)
        else:
            probe_conjs, post_conjs = conjs, []

        def _and_all(cs):
            combined = None
            for c in cs:
                combined = (
                    c if combined is None
                    else ast.BinaryOp("and", combined, c)
                )
            return combined

        combined = _and_all(probe_conjs)
        if combined is not None:
            node, scope = self._apply_where(node, scope, combined)
        node = self._finalize_pool(node, scope)
        for right_rel, on_ast in deferred:
            right_node, right_scope = self._plan_join_child(
                right_rel, outer
            )
            node, scope = self._outer_join_construct(
                node, scope, right_node, right_scope, "left", on_ast
            )
        post = _and_all(post_conjs)
        if post is not None:
            node, scope = self._apply_where(node, scope, post)

        # 3. aggregation / grouping
        agg_map: Dict[ast.Node, str] = {}
        has_agg = any(
            self._contains_agg(it.expr) for it in sel.items
        ) or (sel.having is not None) or bool(sel.group_by)

        if has_agg:
            node, scope, agg_map = self._plan_aggregation(node, scope, sel)

        # 4. window functions
        win_map: Dict[ast.Node, str] = {}
        if any(self._contains_window(it.expr) for it in sel.items):
            node, scope, win_map = self._plan_windows(
                node, scope, sel, agg_map
            )

        # 5. select items -> output projection
        out_names: List[str] = []
        projections: List[Tuple[str, E.Expr]] = []
        for i, item in enumerate(sel.items):
            if isinstance(item.expr, ast.Star):
                qual = item.expr.qualifier
                for name in scope.columns:
                    if name.startswith("$"):
                        continue
                    if qual is not None and name not in scope.qualifiers.get(
                        qual, ()
                    ):
                        continue
                    projections.append(
                        (name, E.ColumnRef(name, scope.columns[name]))
                    )
                    out_names.append(name)
                continue
            e = self._lower(item.expr, scope, agg_map=agg_map, win_map=win_map)
            name = item.alias or self._item_name(item.expr, i)
            projections.append((name, e))
            out_names.append(name)
        # ORDER BY may reference source columns not in the projection —
        # carry them through and slice at output
        order_extra: List[Tuple[str, E.Expr]] = []
        sort_keys: List[SortKey] = []
        if sel.order_by:
            proj_names = {n for n, _ in projections}
            alias_types = {n: e.dtype for n, e in projections}
            for si in sel.order_by:
                key_expr = self._lower_order_key(
                    si.expr, scope, projections, agg_map, win_map
                )
                if isinstance(key_expr, str):  # projection alias reference
                    k = E.ColumnRef(key_expr, alias_types[key_expr])
                else:
                    nm = self._fresh("sort")
                    order_extra.append((nm, key_expr))
                    k = E.ColumnRef(nm, key_expr.dtype)
                if k.dtype.is_nested:
                    raise PlanningError(
                        f"ORDER BY a {k.dtype.name} column is not "
                        "supported"
                    )
                sort_keys.append(
                    SortKey(k, si.descending, si.nulls_first)
                )

        node = N.ProjectNode(node, tuple(projections + order_extra))

        if sel.distinct:
            node = N.DistinctNode(node)

        if sort_keys:
            node = N.SortNode(node, tuple(sort_keys), limit=sel.limit)
        elif sel.limit is not None:
            node = N.LimitNode(node, sel.limit)

        uniq_out = []
        seen = {}
        for n in out_names:
            if n in seen:  # duplicate output names allowed in SQL
                seen[n] += 1
                uniq_out.append((f"{n}_{seen[n]}", n))
            else:
                seen[n] = 0
                uniq_out.append((n, n))
        node = N.OutputNode(node, tuple(uniq_out))
        out_scope = Scope(
            {o: node.output_schema()[o] for o, _ in uniq_out}, {}
        )
        return node, out_scope, tuple(o for o, _ in uniq_out)

    def _const_int(self, e: ast.Node, what: str) -> int:
        lowered = self._lower(e, Scope({}, {}))
        if not isinstance(lowered, E.Literal) or not isinstance(
            lowered.value, int
        ):
            raise PlanningError(f"{what} must be an integer constant")
        return int(lowered.value)

    def _item_name(self, e: ast.Node, i: int) -> str:
        if isinstance(e, ast.Ident):
            return e.parts[-1]
        return f"_col{i}"

    # -------------------------------------------------------------- FROM

    def _plan_from(self, from_, outer, pending_out=None, deferred_out=None):
        """Plan a FROM clause. With ``pending_out`` (a list), ON
        conjuncts of flattened inner joins are APPENDED to it and the
        returned node may be a _PendingJoin — the caller combines them
        with its WHERE so the join pool sees every equi-edge at once
        (one-at-a-time application resolved the pool on the FIRST
        conjunct's edges alone, degrading explicit JOIN..ON chains to
        cross joins + filters). Without it, conjuncts apply here."""
        if from_ is None:
            return N.ValuesNode(), Scope({}, {}, outer)
        rels: List[Tuple[N.PlanNode, Scope]] = []
        structured: List[Tuple[str, ast.Node]] = []  # outer joins

        def flatten(rel):
            if isinstance(rel, ast.JoinRel):
                if rel.join_type in ("cross", "inner"):
                    flatten(rel.left)
                    right_start = len(rels)
                    flatten(rel.right)
                    if rel.on is not None:
                        structured.append(("on", rel.on))
                    return
                # left/right outer joins keep structure
                structured.append(("outer", rel))
                return
            node, scope = self._plan_relation(rel, outer)
            rels.append((node, scope))

        outer_joins: List[ast.JoinRel] = []

        pending_unnests: List[ast.UnnestRef] = []

        def flatten2(rel):
            if isinstance(rel, ast.JoinRel) and rel.join_type in (
                "cross",
                "inner",
            ):
                flatten2(rel.left)
                flatten2(rel.right)
                if rel.on is not None:
                    self._pending_conjuncts.append(rel.on)
                return
            if isinstance(rel, ast.JoinRel):
                if deferred_out is not None and rel.join_type == "left":
                    # defer the LEFT join: flatten its probe spine into
                    # the pool so WHERE equi-edges join it (Q72's week
                    # link), and attach the preserved-side build AFTER
                    # pool resolution — probe-side filters before a
                    # left join are the standard safe pushdown
                    flatten2(rel.left)
                    deferred_out.append((rel.right, rel.on))
                    return
                # plan the outer join as a unit
                node, scope = self._plan_outer_join(rel, outer)
                rels.append((node, scope))
                return
            if isinstance(rel, ast.UnnestRef):
                # lateral: element exprs reference sibling relations, so
                # unnests apply after the join graph is assembled
                pending_unnests.append(rel)
                return
            node, scope = self._plan_relation(rel, outer)
            rels.append((node, scope))

        self._pending_conjuncts: List[ast.Node] = []
        flatten2(from_)

        if not rels:
            # FROM unnest(...) with no other relation
            rels = [(N.ValuesNode(), Scope({}, {}, outer))]

        rels = self._rename_clashes(rels)
        scope = rels[0][1]
        for _, s in rels[1:]:
            scope = scope.merge(s)
        scope.parent = outer

        if len(rels) == 1:
            node = rels[0][0]
        else:
            node = self._join_graph(rels, scope)
        # ON conjuncts of flattened inner joins -> WHERE-style
        # application. Applied BEFORE unnests: ON clauses cannot
        # reference unnest columns (unnest joins are CROSS), and the
        # join pool must see its edges before any unnest caps it.
        pending = self._pending_conjuncts
        self._pending_conjuncts = []
        if pending_out is not None and not pending_unnests:
            # defer: the caller merges these with its WHERE so the
            # pool resolves with the full edge set
            pending_out.extend(pending)
            return node, scope
        if pending:
            combined = pending[0]
            for c in pending[1:]:
                combined = ast.BinaryOp("and", combined, c)
            node, scope = self._apply_where(node, scope, combined)
        for u in pending_unnests:
            node, scope = self._apply_unnest(node, scope, u)
        return node, scope

    def _apply_unnest(self, node, scope: Scope, u: ast.UnnestRef):
        """CROSS JOIN UNNEST(ARRAY[...]) — static-width row expansion
        (see N.UnnestNode). Arrays exist at trace time as expression
        lists; physical array COLUMNS take the column form (per-row
        length expansion under the capacity-bucket protocol)."""
        if isinstance(node, _PendingJoin):
            node = self._finalize_pool(node, scope)
        array_column = None
        els: List[E.Expr] = []
        if isinstance(u.array, ast.ArrayLit):
            if not u.array.items:
                raise PlanningError(
                    "UNNEST of empty ARRAY[] is not supported"
                )
            els = [self._lower(it, scope) for it in u.array.items]
            ct = els[0].dtype
            for el in els[1:]:
                ct = T.common_super_type(ct, el.dtype)
            els = [
                el if el.dtype == ct else E.Cast(el, ct) for el in els
            ]
        else:
            arr = self._lower(u.array, scope)
            if not (
                isinstance(arr, E.ColumnRef) and arr.dtype.is_array
            ):
                raise PlanningError(
                    "UNNEST requires an ARRAY[...] constructor or a "
                    "physical array column"
                )
            array_column = arr.name
            ct = arr.dtype.element
        cols = dict(scope.columns)
        out_internal = (
            u.column if u.column not in cols else self._fresh(u.column)
        )
        cols[out_internal] = ct
        qual = {u.column: out_internal}
        ord_internal = None
        if u.ordinality is not None:
            ord_internal = (
                u.ordinality
                if u.ordinality not in cols
                else self._fresh(u.ordinality)
            )
            cols[ord_internal] = T.BIGINT
            qual[u.ordinality] = ord_internal
        out_cap = None
        if array_column is not None:
            # output bucket: no array-length stats exist, so start at
            # 4x the input estimate; overflow retries scale it
            est = optimizer.estimate_rows(node, self.catalogs)
            out_cap = bucket_capacity(int(est * 4) + 1024)
        node = N.UnnestNode(
            source=node,
            elements=tuple(els),
            out_name=out_internal,
            out_type=ct,
            ordinality_name=ord_internal,
            array_column=array_column,
            out_capacity=out_cap,
        )
        quals = {
            k: dict(v) for k, v in scope.qualifiers.items()
        }
        quals[u.alias] = qual
        return node, Scope(cols, quals, scope.parent)

    def _rename_clashes(self, rels):
        """Self-joined relations expose the same internal column names;
        rename the later relation's clashed columns via a projection so
        plan-level names stay globally unique (alias-qualified lookups
        keep working through the scope's visible-name maps)."""
        seen: Set[str] = set()
        out = []
        for node, s in rels:
            clash = set(s.columns) & seen
            if clash:
                rename = {c: self._fresh(c) for c in clash}
                projs = tuple(
                    (rename.get(c, c), E.ColumnRef(c, t))
                    for c, t in s.columns.items()
                )
                node = N.ProjectNode(node, projs)
                cols = {rename.get(c, c): t for c, t in s.columns.items()}
                quals = {
                    q: {vis: rename.get(i, i) for vis, i in m.items()}
                    for q, m in s.qualifiers.items()
                }
                s = Scope(cols, quals, s.parent)
            seen |= set(s.columns)
            out.append((node, s))
        return out

    def _plan_relation(self, rel, outer):
        if isinstance(rel, ast.TableRef):
            name = rel.parts[-1]
            if len(rel.parts) == 1 and name in self.ctes:
                node, scope, names = self.plan_select(self.ctes[name], outer)
                qual = rel.alias or name
                return node, Scope(
                    dict(node.output_schema()),
                    {qual: {n: n for n in names}},
                    outer,
                )
            catalog = self.session.catalog
            schema = self.session.schema
            if len(rel.parts) == 2:
                schema = rel.parts[0]
            elif len(rel.parts) == 3:
                catalog, schema = rel.parts[0], rel.parts[1]
            handle = TableHandle(catalog, schema, name)
            conn = self.catalogs.get(catalog)
            if rel.version is not None:
                # FOR VERSION AS OF: construct the handle already
                # pinned — pin_snapshot then VALIDATES the id against
                # the connector's committed history (KeyError for an
                # unknown snapshot) instead of picking the tip. A
                # connector without snapshot support inherits the
                # default pin_snapshot, which ignores the pin and
                # would silently serve live rows — reject it here.
                handle = dataclasses.replace(
                    handle, snapshot=rel.version
                )
                if type(conn).pin_snapshot is Connector.pin_snapshot:
                    raise PlanningError(
                        f"catalog {catalog!r} does not support "
                        "FOR VERSION AS OF"
                    )
                try:
                    handle = conn.pin_snapshot(handle)
                except KeyError as e:
                    raise PlanningError(str(e.args[0]) if e.args else str(e))
            else:
                # snapshot-capable connectors (streaming ingest) pin
                # the scan to the tip committed version HERE, once per
                # plan: every split, staged page, and capacity retry
                # then reads one immutable prefix — readers never see
                # a torn batch, and long scans are isolated from
                # concurrent commits. Default connectors return the
                # handle unchanged.
                handle = conn.pin_snapshot(handle)
            tschema = conn.metadata().get_table_schema(handle)
            node = N.TableScanNode(
                handle=handle,
                columns=tuple(tschema),
                schema=tuple(tschema.items()),
            )
            qual = rel.alias or name
            return node, Scope(
                tschema, {qual: {c: c for c in tschema}}, outer
            )
        if isinstance(rel, ast.SubqueryRef):
            node, scope, names = self.plan_select(rel.query, outer)
            return node, Scope(
                dict(node.output_schema()),
                {rel.alias: {n: n for n in names}},
                outer,
            )
        if isinstance(rel, ast.UnionRel):
            return self._plan_union(rel, outer)
        if isinstance(rel, ast.ValuesRel):
            return self._plan_values(rel, outer)
        raise PlanningError(f"unsupported relation {type(rel).__name__}")

    def _plan_values(self, rel: ast.ValuesRel, outer):
        """(VALUES ...) AS t(c1, ...): an inline table as a UNION ALL
        of single-row literal projections over the FROM-less relation
        (reference: Values query body) — zero new executor surface."""
        if not rel.rows:
            raise PlanningError("VALUES requires at least one row")
        arity = len(rel.rows[0])
        for row in rel.rows:
            if len(row) != arity:
                raise PlanningError(
                    "VALUES rows must have equal arity "
                    f"({arity} vs {len(row)})"
                )
        if rel.column_names and len(rel.column_names) != arity:
            raise PlanningError(
                f"VALUES alias declares {len(rel.column_names)} "
                f"columns for {arity}-column rows"
            )
        empty = Scope({}, {}, None)
        lowered = [
            [self._lower(e, empty) for e in row] for row in rel.rows
        ]
        types = []
        for i in range(arity):
            ct = lowered[0][i].dtype
            for row in lowered[1:]:
                # typed NULLs coerce toward the non-null type
                if isinstance(row[i], E.Literal) and row[i].value is None:
                    continue
                if isinstance(lowered[0][i], E.Literal) and (
                    lowered[0][i].value is None
                ):
                    ct = row[i].dtype
                    continue
                ct = T.common_super_type(ct, row[i].dtype)
            if ct.is_long_decimal:
                # the bigint/decimal lattice widens mixed integer +
                # decimal literals past p=18; VALUES literals always
                # fit the short form
                ct = T.decimal(18, ct.scale)
            types.append(ct)
        visible = tuple(rel.column_names) or tuple(
            f"_col{i}" for i in range(arity)
        )
        internal = tuple(self._fresh(v.lstrip("$")) for v in visible)
        row_nodes = []
        for row in lowered:
            projs = []
            for i, e in enumerate(row):
                if isinstance(e, E.Literal) and e.value is None:
                    e = E.Literal(None, types[i])
                elif e.dtype != types[i]:
                    e = (
                        _coerce_literal(e, types[i])
                        if isinstance(e, E.Literal)
                        and not types[i].is_string
                        else E.Cast(e, types[i])
                    )
                projs.append((internal[i], e))
            row_nodes.append(
                N.ProjectNode(source=N.ValuesNode(), projections=tuple(projs))
            )
        node = (
            row_nodes[0]
            if len(row_nodes) == 1
            else N.UnionAllNode(sources=tuple(row_nodes))
        )
        scope = Scope(
            {n: t for n, t in zip(internal, types)},
            {rel.alias: dict(zip(visible, internal))},
            outer,
        )
        return node, scope

    def _plan_union(self, rel: ast.UnionRel, outer):
        """Set operations (reference: UNION [ALL] via UnionNode +
        SetOperationNode rewrites): plan each term, align columns
        POSITIONALLY to the first term's names and the common super
        types (projection + cast per term), concatenate with
        UnionAllNode, and fold a DistinctNode after every non-ALL op
        (left-associative, standard semantics)."""
        planned = []
        for t in rel.terms:
            node, _, names = self.plan_select(t, outer=outer)
            planned.append((node, names))
        arity = len(planned[0][1])
        for node, names in planned[1:]:
            if len(names) != arity:
                raise PlanningError(
                    "UNION terms must have the same number of columns "
                    f"({arity} vs {len(names)})"
                )
        # common types per position. A term column that is a bare NULL
        # literal (reference: UNKNOWN type coercing to anything) does
        # not vote — it adopts the other terms' type; the grouping-sets
        # desugar emits exactly this shape for absent group columns
        def _null_literal_expr(node, name):
            while isinstance(node, N.OutputNode):
                name = dict(node.columns).get(name, name)
                node = node.source
            if isinstance(node, N.ProjectNode):
                e = dict(node.projections).get(name)
                if isinstance(e, E.Literal) and e.value is None:
                    return e
            return None

        types = []
        for i in range(arity):
            ct = None
            for node, names in planned:
                if _null_literal_expr(node, names[i]) is not None:
                    continue
                t_i = node.output_schema()[names[i]]
                ct = t_i if ct is None else T.common_super_type(ct, t_i)
            types.append(ct if ct is not None else T.BIGINT)
        # canonical output names: the first term's visible names
        # (de-duplicated — they become this relation's columns)
        out_names: List[str] = []
        seen: Set[str] = set()
        for n in planned[0][1]:
            nm = n if n not in seen else self._fresh(n.lstrip("$"))
            seen.add(nm)
            out_names.append(nm)
        aligned = []
        for node, names in planned:
            schema = node.output_schema()
            projs = []
            for i, out in enumerate(out_names):
                if (
                    _null_literal_expr(node, names[i]) is not None
                    and schema[names[i]] != types[i]
                ):
                    # retype the NULL in place — no runtime cast kernel
                    projs.append((out, E.Literal(None, types[i])))
                    continue
                src = E.ColumnRef(names[i], schema[names[i]])
                e = src if src.dtype == types[i] else E.Cast(
                    src, types[i]
                )
                projs.append((out, e))
            aligned.append(N.ProjectNode(node, tuple(projs)))
        cur = aligned[0]
        for node, op in zip(aligned[1:], rel.ops):
            if op in ("union_all", "union"):
                cur = N.UnionAllNode(sources=(cur, node))
                if op == "union":
                    cur = N.DistinctNode(
                        source=cur, max_groups=self._agg_bucket(cur)
                    )
            else:  # intersect | except (DISTINCT semantics)
                cur = self._set_difference(
                    cur, node, out_names, types, keep_both=(op == "intersect")
                )
        scope = Scope(
            {n: t for n, t in zip(out_names, types)}, {}, outer
        )
        return cur, scope

    def _set_difference(self, left, right, out_names, types, keep_both):
        """INTERSECT / EXCEPT (DISTINCT semantics) without a dedicated
        kernel: tag each side, UNION ALL (which re-encodes string
        columns into ONE dictionary, making all-column grouping valid),
        group by every output column tracking per-side presence, and
        keep groups present on both sides (INTERSECT) or only the left
        (EXCEPT) — the reference's SetOperationNode-to-aggregation
        rewrite, TPU-first over the existing union + sorted-agg
        kernels."""
        tag = self._fresh("setop")
        tagged = []
        for node, tag_val in ((left, 1), (right, 2)):
            schema = node.output_schema()
            tagged.append(
                N.ProjectNode(
                    source=node,
                    projections=tuple(
                        (n, E.ColumnRef(n, schema[n])) for n in out_names
                    )
                    + ((tag, E.Literal(tag_val, T.INTEGER)),),
                )
            )
        u = N.UnionAllNode(sources=tuple(tagged))
        tag_ref = E.ColumnRef(tag, T.INTEGER)
        lo, hi = self._fresh("tagmin"), self._fresh("tagmax")
        agg = N.AggregationNode(
            source=u,
            group_keys=tuple(
                (n, E.ColumnRef(n, t))
                for n, t in zip(out_names, types)
            ),
            aggs=(
                AggCall("min", tag_ref, lo),
                AggCall("max", tag_ref, hi),
            ),
            max_groups=self._agg_bucket(u),
        )
        # tags are 1 (left) / 2 (right): INTERSECT keeps groups seen on
        # both sides (min=1 AND max=2); EXCEPT keeps left-only (max=1)
        if keep_both:
            pred: E.Expr = E.And(
                (
                    E.Compare(
                        "=", E.ColumnRef(lo, T.INTEGER),
                        E.Literal(1, T.INTEGER),
                    ),
                    E.Compare(
                        "=", E.ColumnRef(hi, T.INTEGER),
                        E.Literal(2, T.INTEGER),
                    ),
                )
            )
        else:
            pred = E.Compare(
                "=", E.ColumnRef(hi, T.INTEGER), E.Literal(1, T.INTEGER)
            )
        filtered = N.FilterNode(source=agg, predicate=pred)
        return N.ProjectNode(
            source=filtered,
            projections=tuple(
                (n, E.ColumnRef(n, t))
                for n, t in zip(out_names, types)
            ),
        )

    def _plan_join_child(self, rel, outer):
        """One side of an outer join: a leaf relation, a nested outer
        join, or an INNER/CROSS JoinRel chain (the Q72 shape `a join b
        on ... left join c`) planned through the flatten machinery —
        saving the in-flight conjunct state the nested _plan_from call
        would otherwise clobber."""
        if isinstance(rel, ast.JoinRel):
            if rel.join_type in ("cross", "inner"):
                saved = self._pending_conjuncts
                try:
                    node, scope = self._plan_from(rel, outer)
                finally:
                    self._pending_conjuncts = saved
                if isinstance(node, _PendingJoin):
                    node = self._finalize_pool(node, scope)
                return node, scope
            return self._plan_outer_join(rel, outer)
        return self._plan_relation(rel, outer)

    def _plan_outer_join(self, rel: ast.JoinRel, outer):
        jt = rel.join_type
        left_node, left_scope = self._plan_join_child(rel.left, outer)
        right_node, right_scope = self._plan_join_child(
            rel.right, outer
        )
        if jt == "right":  # normalize: probe side is preserved side
            left_node, right_node = right_node, left_node
            left_scope, right_scope = right_scope, left_scope
            jt = "left"
        if jt not in ("left", "full"):
            raise PlanningError(f"unsupported join type: {rel.join_type}")
        return self._outer_join_construct(
            left_node, left_scope, right_node, right_scope, jt, rel.on
        )

    def _outer_join_construct(
        self, left_node, left_scope, right_node, right_scope, jt, on
    ):
        """Build the LEFT/FULL JoinNode given both planned sides — the
        shared tail of _plan_outer_join and the deferred-outer-join
        path (probe side resolved first so WHERE equi-edges join its
        pool)."""
        (left_node, left_scope), (right_node, right_scope) = (
            self._rename_clashes(
                [(left_node, left_scope), (right_node, right_scope)]
            )
        )
        scope = left_scope.merge(right_scope)
        conjs = _split_conjuncts(on)
        lkeys, rkeys, build_filters, residual = [], [], [], []
        for c in conjs:
            pair = self._as_equi_pair(c, left_scope, right_scope)
            if pair:
                lkeys.append(pair[0])
                rkeys.append(pair[1])
                continue
            # ON conjuncts touching only the build side restrict MATCHING
            # (not output rows): push them into the build side pre-join —
            # the Q13 `left join ... on ... and o_comment not like ...`
            # shape. Probe-side or mixed residuals on outer joins would
            # change preserved-row semantics: unsupported this round.
            try:
                build_filters.append(self._lower(c, right_scope))
                continue
            except PlanningError:
                pass
            residual.append(c)
        if residual:
            raise PlanningError(
                "LEFT JOIN ON conditions touching the probe side beyond "
                "equi keys are not supported yet"
            )
        if not lkeys:
            raise PlanningError("outer join requires at least one equi key")
        lsch = dict(left_scope.columns)
        for k in lkeys:
            if lsch[k].is_long_decimal:
                # preserved-row semantics leave no place to apply a
                # residual collision filter over the 128->64 key mix
                raise PlanningError(
                    "outer join on a long decimal (p>18) key is not "
                    "supported (documented deviation; cast to "
                    "decimal(18,s))"
                )
        if build_filters and jt == "full":
            # pushing an ON filter into the build side is only sound when
            # the build's unmatched rows are dropped (left) — a FULL join
            # preserves them, so the rewrite would change results
            raise PlanningError(
                "FULL JOIN ON conditions beyond equi keys are not "
                "supported yet"
            )
        if build_filters:
            right_node = N.FilterNode(
                right_node,
                build_filters[0]
                if len(build_filters) == 1
                else E.And(tuple(build_filters)),
            )
        payload = tuple(right_scope.columns)
        forced_unique = None
        if len(lkeys) > 2:
            # the kernel key is a 2x32-bit composite: wider outer-join
            # keys must pack bijectively (stats-allocated bit widths);
            # residual demotion is NOT available here — an outer join's
            # preserved rows leave no place to re-check demoted keys
            packed = self._pack_composite_keys(
                left_node, right_node, list(zip(lkeys, rkeys))
            )
            if packed is None:
                raise PlanningError(
                    ">2 outer-join key columns need stats-backed "
                    "bijective packing (unavailable here)"
                )
            left_node, right_node, pairs2, forced_unique = packed
            lkeys = [p[0] for p in pairs2]
            rkeys = [p[1] for p in pairs2]
        unique = (
            forced_unique
            if forced_unique is not None
            else optimizer.is_build_unique(
                right_node, tuple(rkeys), self.catalogs
            )
        )
        out_cap = None
        if not unique:
            probe_est = optimizer.estimate_rows(left_node, self.catalogs)
            build_est = optimizer.estimate_rows(right_node, self.catalogs)
            out_cap = bucket_capacity(
                int(max(probe_est, build_est) * 4) + 1024
            )
        node = N.JoinNode(
            left=left_node,
            right=right_node,
            join_type=jt,
            left_keys=tuple(lkeys),
            right_keys=tuple(rkeys),
            payload=payload,
            build_unique=unique,
            out_capacity=out_cap,
        )
        return node, scope

    def _as_equi_pair(self, c, left_scope, right_scope):
        if not (isinstance(c, ast.BinaryOp) and c.op == "="):
            return None
        if not (
            isinstance(c.left, ast.Ident) and isinstance(c.right, ast.Ident)
        ):
            return None
        try:
            ln, _, lo = left_scope.resolve(c.left.parts)
            rn, _, ro = right_scope.resolve(c.right.parts)
            if not lo and not ro:
                return (ln, rn)
        except PlanningError:
            pass
        try:
            ln, _, lo = left_scope.resolve(c.right.parts)
            rn, _, ro = right_scope.resolve(c.left.parts)
            if not lo and not ro:
                return (ln, rn)
        except PlanningError:
            return None
        return None

    # --------------------------------------------------------- join graph

    def _join_graph(self, rels, scope: Scope) -> N.PlanNode:
        """Defer: equi-edges arrive with WHERE/ON conjuncts; the pool is
        resolved in _apply_where (or finalized without edges)."""
        return _PendingJoin(tuple(r[0] for r in rels), tuple(r[1] for r in rels))

    # ----------------------------------------------------- WHERE / subquery

    def _apply_where(self, node, scope: Scope, where_ast) -> Tuple[N.PlanNode, Scope]:
        conjuncts = [
            f for c in _split_conjuncts(where_ast) for f in _factor_or(c)
        ]
        subq_ops = []
        plain = []
        marked = []
        for c in conjuncts:
            m = self._match_subquery_conjunct(c, scope)
            if m is not None:
                subq_ops.append(m)
            elif _contains_membership_subquery(c):
                marked.append(c)
            else:
                plain.append(c)
        if isinstance(node, _PendingJoin):
            node = self._resolve_join_pool(node, scope, plain)
        elif plain:
            preds = [self._lower(c, scope) for c in plain]
            node = N.FilterNode(
                node, preds[0] if len(preds) == 1 else E.And(tuple(preds))
            )
        for c in marked:
            node = self._finalize_pool(node, scope)
            node, scope = self._apply_mark_join_conjunct(node, scope, c)
        for op in subq_ops:
            node, scope = self._apply_subquery_op(node, scope, op)
        return node, scope

    def _apply_mark_join_conjunct(self, node, scope, c):
        """OR-embedded IN-subquery / EXISTS predicates via MARK joins
        (reference: SemiJoinNode's semiJoinOutput column): each
        subquery attaches as a LEFT join against the DISTINCT inner
        rows carrying a constant marker payload, and the predicate
        lowers with the subquery replaced by a `marker IS NOT NULL`
        test (the Q45 `zip-list OR item IN (subquery)` and Q10/Q35
        `exists(...) or exists(...)` shapes). Positive polarity only:
        under a WHERE filter, UNKNOWN and FALSE coincide, so the
        marker test is exact; a subquery under NOT would need
        three-valued null-awareness and raises instead."""

        def attach(sub, negated):
            nonlocal node, scope
            if isinstance(sub, ast.InSubquery):
                # the marker test collapses UNKNOWN to FALSE — exact
                # only for a non-negated IN in positive polarity
                if negated or sub.negate:
                    raise PlanningError(
                        "NOT IN (or IN under NOT) inside OR requires "
                        "null-aware three-valued semantics "
                        "(unsupported)"
                    )
                if self._is_correlated(sub.query, scope):
                    raise PlanningError(
                        "correlated IN under OR is not supported"
                    )
                sub_node, _, sub_names = self.plan_select(
                    sub.query, outer=None
                )
                if len(sub_names) != 1:
                    raise PlanningError(
                        "IN subquery must return one column"
                    )
                node, scope, key = self._probe_key(node, scope, sub.arg)
                if scope.columns[key].is_long_decimal:
                    raise PlanningError(
                        "IN on a long decimal (p>18) is not supported"
                    )
                outer_keys = (key,)
                right_keys = tuple(sub_names)
                build = sub_node
                invert = False
            elif isinstance(sub, ast.Exists):
                q = sub.query
                if q.group_by or q.having:
                    raise PlanningError(
                        "EXISTS with GROUP BY/HAVING under OR is not "
                        "supported"
                    )
                corr_pairs, residual_where = self._extract_correlation(
                    q, scope
                )
                if not corr_pairs:
                    raise PlanningError(
                        "uncorrelated or non-equality-correlated "
                        "EXISTS under OR is not supported"
                    )
                inner_cols = tuple(p[0] for p in corr_pairs)
                inner_sel = ast.Select(
                    items=tuple(
                        ast.SelectItem(ast.Ident((ic,)), None)
                        for ic in inner_cols
                    ),
                    from_=q.from_,
                    where=residual_where,
                    ctes=q.ctes,
                )
                build, _, right_keys = self.plan_select(
                    inner_sel, outer=None
                )
                right_keys = tuple(right_keys)
                outer_keys = tuple(p[1] for p in corr_pairs)
                # return THIS node's truth value (enclosing NOTs stay
                # in the tree and invert it); NOT EXISTS is 2-valued,
                # so inverting the marker is exact
                invert = sub.negate
            else:
                raise PlanningError(
                    "unsupported subquery shape under OR"
                )
            for k in outer_keys:
                if scope.columns[k].is_long_decimal:
                    raise PlanningError(
                        "mark join on a long decimal key is not "
                        "supported"
                    )
            marker = self._fresh("mark")
            bschema = dict(build.output_schema())
            build = N.DistinctNode(
                source=build, max_groups=self._agg_bucket(build)
            )
            build = N.ProjectNode(
                build,
                tuple(
                    (n, E.ColumnRef(n, bschema[n])) for n in right_keys
                )
                + ((marker, E.Literal(1, T.BIGINT)),),
            )
            node = N.JoinNode(
                left=node,
                right=build,
                join_type="left",
                left_keys=outer_keys,
                right_keys=right_keys,
                payload=(marker,),
                build_unique=True,
            )
            scope = Scope(
                {**scope.columns, marker: T.BIGINT},
                scope.qualifiers,
                scope.parent,
            )
            return ast.IsNullExpr(
                ast.Ident((marker,)), negate=not invert
            )

        def rewrite(n, negated):
            if isinstance(n, (ast.InSubquery, ast.Exists)):
                return attach(n, negated)
            if isinstance(n, ast.UnaryOp) and n.op == "not":
                return dataclasses.replace(
                    n, arg=rewrite(n.arg, not negated)
                )
            if isinstance(n, ast.Select) or not isinstance(n, ast.Node):
                return n
            kwargs = {}
            changed = False
            for f in dataclasses.fields(n):
                v = getattr(n, f.name)
                if isinstance(v, ast.Node):
                    nv = rewrite(v, negated)
                elif isinstance(v, tuple):
                    nv = tuple(
                        rewrite(x, negated)
                        if isinstance(x, ast.Node)
                        else x
                        for x in v
                    )
                else:
                    nv = v
                kwargs[f.name] = nv
                changed |= nv is not v
            return dataclasses.replace(n, **kwargs) if changed else n

        rewritten = rewrite(c, False)
        pred = self._lower(rewritten, scope)
        return N.FilterNode(node, pred), scope


    def _resolvable_in(self, c, scope: Scope) -> bool:
        """True when every column reference in ``c`` (outside nested
        Select bodies) resolves in ``scope`` — the classifier that
        decides whether a WHERE conjunct pushes below deferred LEFT
        joins."""
        ok = True

        def visit(n):
            nonlocal ok
            if not ok or not isinstance(n, ast.Node):
                return
            if isinstance(n, ast.Select):
                return
            if isinstance(n, ast.Ident):
                try:
                    scope.resolve(n.parts)
                except PlanningError:
                    ok = False
                return
            for f in dataclasses.fields(n):
                v = getattr(n, f.name)
                if isinstance(v, ast.Node):
                    visit(v)
                elif isinstance(v, tuple):
                    for x in v:
                        if isinstance(x, ast.Node):
                            visit(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, ast.Node):
                                    visit(y)
        visit(c)
        return ok

    @staticmethod
    def _edge_connected(indices, edges) -> bool:
        """True when ``indices`` form one connected component under
        ``edges`` — the bushy rescue must not cross-join unrelated
        relations into its subtree."""
        indices = set(indices)
        if len(indices) <= 1:
            return True
        adj: Dict[int, Set[int]] = {i: set() for i in indices}
        for (i, j, _ci, _cj) in edges:
            if i in indices and j in indices:
                adj[i].add(j)
                adj[j].add(i)
        seen = set()
        stack = [next(iter(indices))]
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            stack.extend(adj[n] - seen)
        return seen == indices

    def _grow_join_tree(
        self, tree, joined, remaining, rels, scopes, est, edges, grow
    ):
        """The greedy left-deep join loop over a shared relation pool
        (indices into ``rels``/``est``; ``edges`` as (i, j, col_i,
        col_j)). ``grow`` re-enters this method for the bushy rescue:
        when the best edged candidate explodes (Q72's inventory x
        catalog_sales on item alone), the REMAINING relations resolve
        into their own subtree first, which then attaches as one
        pseudo-relation over every crossing edge — the composite
        (item, week) join the reference's CBO produces."""
        while remaining:
            # edges from joined set to a candidate relation
            cand: Dict[int, List[Tuple[str, str]]] = {}
            for (i, j, ci, cj) in edges:
                if i in joined and j in remaining:
                    cand.setdefault(j, []).append((ci, cj))
                elif j in joined and i in remaining:
                    cand.setdefault(i, []).append((cj, ci))
            if not cand:
                # no equi edge: cross join. Single-row builds broadcast
                # (scalar-aggregate shape, no expansion); multi-row
                # builds take the general nested-loop kernel with a
                # stats-sized output bucket (reference:
                # NestedLoopJoinOperator)
                nxt = min(remaining, key=lambda i: est[i])
                if est[nxt] > 1.5:
                    tree_est = optimizer.estimate_rows(
                        tree, self.catalogs
                    )
                    cap = bucket_capacity(
                        int(max(tree_est, 1) * max(est[nxt], 1) * 1.2)
                        + 1024
                    )
                    tree = N.CrossJoinNode(
                        tree, rels[nxt], out_capacity=cap
                    )
                else:
                    tree = N.CrossJoinNode(tree, rels[nxt])
                remaining.discard(nxt)
                joined.add(nxt)
                continue
            # cost-based greedy (reference: ReorderJoins'
            # min-intermediate-cardinality objective, greedy instead of
            # DP): pick the candidate whose join OUTPUT estimate is
            # smallest — a selective non-unique build beats a huge PK
            # build on star joins (the Q64-class bad-greedy-pick guard,
            # VERDICT r3 weak 7). Unique builds keep the probe
            # cardinality and take the kernel's static-shape fast
            # path, so they tie-break first.
            tree_est = optimizer.estimate_rows(tree, self.catalogs)

            def rank(i):
                keys = tuple(p[1] for p in cand[i])
                unique = optimizer.is_build_unique(
                    rels[i], keys, self.catalogs
                )
                if unique:
                    out_est = tree_est
                else:
                    # FK-join shape: output ~ probe * build / NDV(keys)
                    ndv = 1.0
                    for k in keys:
                        cs = optimizer._column_stats(
                            rels[i], k, self.catalogs
                        )
                        if cs and cs.distinct_count:
                            ndv *= float(cs.distinct_count)
                    ndv = max(min(ndv, est[i]), 1.0)
                    out_est = tree_est * est[i] / ndv
                return (out_est, not unique, est[i])

            nxt = min(cand, key=rank)
            out_est_nxt, nxt_nonunique, _ = rank(nxt)
            if (
                grow is not None
                and nxt_nonunique
                and len(remaining) >= 2
                and out_est_nxt > 8.0 * max(tree_est, 1024.0)
                and out_est_nxt > float(1 << 20)
                and self._edge_connected(remaining, edges)
            ):
                # bushy rescue: the best edged candidate fans out
                # (Q72: inventory x catalog_sales on item alone,
                # probe*build/NDV ~ 9M). Resolve the REMAINING
                # relations into their own subtree first, then attach
                # it as ONE pseudo-relation — every tree<->subtree
                # edge (item AND the d1/d2 week link) composites into
                # a single selective join, the shape the reference's
                # CBO produces for this plan.
                sub_set = frozenset(remaining)
                sub_seed = max(sub_set, key=lambda i: est[i])
                sub_tree = grow(
                    rels[sub_seed],
                    {sub_seed},
                    set(sub_set) - {sub_seed},
                )
                new_i = len(rels)
                rels.append(sub_tree)
                scopes.append(
                    Scope(dict(sub_tree.output_schema()), {}, None)
                )
                est.append(
                    optimizer.estimate_rows(sub_tree, self.catalogs)
                )
                remapped = []
                for (i, j, ci, cj) in edges:
                    ii = new_i if i in sub_set else i
                    jj = new_i if j in sub_set else j
                    if ii == jj:
                        continue  # consumed inside the subtree
                    remapped.append((ii, jj, ci, cj))
                edges[:] = remapped
                remaining = {new_i}
                continue
            pairs = cand[nxt]
            build = rels[nxt]
            extra_pairs: List[Tuple[str, str]] = []
            forced_unique = None
            tree_sch = dict(tree.output_schema())
            for ci, _ in pairs:
                if tree_sch[ci].is_nested:
                    raise PlanningError(
                        f"join on a {tree_sch[ci].name} column is not "
                        "supported"
                    )
            ld_pairs = [
                p for p in pairs if tree_sch[p[0]].is_long_decimal
            ]
            if ld_pairs:
                # long-decimal (int128) equi keys: the kernel key is a
                # 128->64 mix (ops.join._key_of), so EVERY long-decimal
                # pair — including one used as the kernel key — is also
                # demoted to a residual limb-equality filter; a mix
                # collision becomes a filtered row, never a wrong one.
                # Inner joins only (this pool is inner by construction).
                norm = [p for p in pairs if p not in ld_pairs]
                extra_pairs.extend(ld_pairs)
                if norm:
                    pairs = norm
                else:
                    pairs = ld_pairs[:1]
                    # the mix can collide: never trust m in {0,1}
                    forced_unique = False
            if len(pairs) > 2:
                # widen past the kernel's 2x32-bit composite: when
                # connector stats bound every key column's range, the
                # whole composite packs BIJECTIVELY into one bigint
                # via stats-allocated bit widths (no residual, no
                # out_capacity blow-out on skew)
                packed = self._pack_composite_keys(tree, build, pairs)
                if packed is not None:
                    tree, build, pairs, forced_unique = packed
                else:
                    # fallback: keep the subset that proves build
                    # uniqueness and demote the rest to a residual
                    import itertools

                    best = None
                    for combo in itertools.combinations(
                        range(len(pairs)), 2
                    ):
                        keys = tuple(pairs[k][1] for k in combo)
                        if optimizer.is_build_unique(
                            build, keys, self.catalogs
                        ):
                            best = combo
                            break
                    if best is None:
                        best = (0, 1)
                    extra_pairs.extend(
                        p for k, p in enumerate(pairs) if k not in best
                    )
                    pairs = [pairs[k] for k in best]
            lkeys = tuple(p[0] for p in pairs)
            rkeys = tuple(p[1] for p in pairs)
            unique = (
                forced_unique
                if forced_unique is not None
                else optimizer.is_build_unique(
                    build, rkeys, self.catalogs
                )
            )
            payload = tuple(
                c for c in build.output_schema() if c not in rkeys
            ) + tuple(c for c in rkeys if c not in tree.output_schema())
            # keep join keys from the build side only when names don't clash
            payload = tuple(
                c for c in build.output_schema()
                if c not in tree.output_schema()
            )
            out_cap = None
            if not unique:
                probe_est = optimizer.estimate_rows(tree, self.catalogs)
                build_est = est[nxt]
                # stats-driven OUTPUT estimate (the ranker's FK-join
                # formula over the kernel keys): a fan-out join like
                # Q72's inventory x catalog_sales on item alone
                # produces probe*build/NDV rows — sizing from inputs
                # only sent it through the 4x capacity-retry loop,
                # recompiling the whole program at each step
                ndv = 1.0
                saw_stats = False
                for k in rkeys:
                    cs_ = optimizer._column_stats(
                        build, k, self.catalogs
                    )
                    if cs_ and cs_.distinct_count:
                        ndv *= float(cs_.distinct_count)
                        saw_stats = True
                ndv = max(min(ndv, build_est), 1.0)
                # ndv=1 with NO stats means "no information", not "one
                # distinct value" — only widen the bucket beyond the
                # input-sized default when stats actually back the
                # fan-out estimate (a stats-less guess of probe*build
                # compiled a 268M-row program for a 2k-row join)
                cap_est = int(max(probe_est, build_est) * 4)
                if saw_stats:
                    out_est = probe_est * build_est / ndv
                    cap_est = max(cap_est, int(out_est * 3 / 2))
                out_cap = bucket_capacity(cap_est + 1024)
            join_residual = None
            if extra_pairs:
                tree_schema = dict(tree.output_schema())
                build_schema = dict(build.output_schema())
                eqs = []
                for ci, cj in extra_pairs:
                    if cj not in payload:
                        raise PlanningError(
                            f"demoted join key {cj} not carried in the "
                            "join payload (name clash)"
                        )
                    eqs.append(
                        E.Compare(
                            "=",
                            E.ColumnRef(ci, tree_schema[ci]),
                            E.ColumnRef(cj, build_schema[cj]),
                        )
                    )
                join_residual = (
                    eqs[0] if len(eqs) == 1 else E.And(tuple(eqs))
                )
            tree = N.JoinNode(
                left=tree,
                right=build,
                join_type="inner",
                left_keys=lkeys,
                right_keys=rkeys,
                payload=payload,
                build_unique=unique,
                out_capacity=out_cap,
                residual=join_residual,
            )
            joined.add(nxt)
            remaining.discard(nxt)
        return tree

    def _finalize_pool(self, node, scope):
        if isinstance(node, _PendingJoin):
            node = self._resolve_join_pool(node, scope, [])
        return node

    def _resolve_join_pool(
        self, pool: "_PendingJoin", scope: Scope, conjuncts
    ) -> N.PlanNode:
        rels = list(pool.rels)
        scopes = list(pool.scopes)
        # ownership map: column/qualified name -> relation index
        owner: Dict[str, int] = {}
        for i, s in enumerate(scopes):
            for c in s.columns:
                owner[c] = i

        def rels_of(c) -> Set[int]:
            found: Set[int] = set()

            def visit(n):
                if isinstance(n, ast.Ident):
                    for i, s in enumerate(scopes):
                        try:
                            _, _, is_outer = s.resolve(n.parts)
                            if not is_outer:
                                found.add(i)
                                return
                        except PlanningError:
                            continue
                    return
                for f in dataclasses.fields(n) if dataclasses.is_dataclass(n) else []:
                    v = getattr(n, f.name)
                    if isinstance(v, ast.Node):
                        visit(v)
                    elif isinstance(v, tuple):
                        for x in v:
                            if isinstance(x, ast.Node):
                                visit(x)
                            elif (
                                isinstance(x, tuple)
                                and len(x) == 2
                                and all(isinstance(y, ast.Node) for y in x)
                            ):
                                visit(x[0])
                                visit(x[1])
            visit(c)
            return found

        filters: Dict[int, List] = {}
        edges: List[Tuple[int, int, str, str]] = []  # (i, j, col_i, col_j)
        residual: List = []
        for c in conjuncts:
            rs = rels_of(c)
            if len(rs) == 1:
                filters.setdefault(next(iter(rs)), []).append(c)
            elif (
                len(rs) == 2
                and isinstance(c, ast.BinaryOp)
                and c.op == "="
                and isinstance(c.left, ast.Ident)
                and isinstance(c.right, ast.Ident)
            ):
                i = next(iter(rels_of(c.left)))
                j = next(iter(rels_of(c.right)))
                li, _, _ = scopes[i].resolve(c.left.parts)
                rj, _, _ = scopes[j].resolve(c.right.parts)
                edges.append((i, j, li, rj))
            else:
                residual.append(c)

        for i, fs in filters.items():
            preds = [self._lower(f, scopes[i]) for f in fs]
            rels[i] = N.FilterNode(
                rels[i], preds[0] if len(preds) == 1 else E.And(tuple(preds))
            )

        est = [optimizer.estimate_rows(r, self.catalogs) for r in rels]

        def grow_sub(tree, joined, remaining):
            # the subtree grower NEVER rescues: a nested rescue's
            # in-place edge remap would orphan crossing edges of the
            # outer rescue (silently dropping join predicates)
            return self._grow_join_tree(
                tree, joined, remaining, rels, scopes, est, edges,
                grow=None,
            )

        def grow(tree, joined, remaining):
            return self._grow_join_tree(
                tree, joined, remaining, rels, scopes, est, edges,
                grow=grow_sub,
            )

        joined = {max(range(len(rels)), key=lambda i: est[i])}
        tree = rels[next(iter(joined))]
        remaining = set(range(len(rels))) - joined
        tree = grow(tree, joined, remaining)

        if residual:
            preds = [self._lower(c, scope) for c in residual]
            tree = N.FilterNode(
                tree, preds[0] if len(preds) == 1 else E.And(tuple(preds))
            )
        return tree

    # ----------------------------------------------- subquery conjunct ops

    def _match_subquery_conjunct(self, c, scope):
        negate = False
        inner = c
        if isinstance(inner, ast.UnaryOp) and inner.op == "not":
            negate = True
            inner = inner.arg
        if isinstance(inner, ast.InSubquery):
            return ("in", inner, negate != inner.negate)
        if isinstance(inner, ast.Exists):
            return ("exists", inner, negate != inner.negate)
        if (
            isinstance(inner, ast.BinaryOp)
            and inner.op in ("=", "<>", "!=", "<", "<=", ">", ">=")
            and not negate
        ):
            # the subquery may sit anywhere inside the comparison
            # (q6-class: i_current_price > 1.2 * (select avg(...))) —
            # exactly one CORRELATED ScalarSubquery qualifies;
            # uncorrelated siblings keep lowering via Param
            subs = [
                s
                for s in _find_scalar_subqueries(inner)
                if self._is_correlated(s.query, scope)
            ]
            if len(subs) == 1:
                return ("scalar_cmp", inner, False)
            return None  # uncorrelated: handled by Param in _lower
        return None

    def _is_correlated(self, q: ast.Select, scope: Scope) -> bool:
        saved_params = list(self.params)
        try:
            self.plan_select(q, outer=None)
            return False
        except PlanningError:
            return True
        finally:
            self.params = saved_params

    def _apply_subquery_op(self, node, scope, op):
        kind, a, negate = op
        node = self._finalize_pool(node, scope)
        if kind == "in":
            return self._apply_in_subquery(node, scope, a, negate)
        if kind == "exists":
            return self._apply_exists(node, scope, a, negate)
        if kind == "scalar_cmp":
            return self._apply_correlated_scalar(node, scope, a)
        raise AssertionError(kind)

    def _pack_composite_keys(self, tree, build, pairs):
        """>2-column equi-join keys -> ONE synthetic bigint key on each
        side, packed bijectively with stats-allocated bit widths
        (reference: multi-channel GroupByHash/JoinProbe composite keys;
        TPU-first: the sorted-probe kernel stays single-int64).

        Requires every pair to be integer/date-typed with known
        min/max on BOTH sides and a total packed width <= 62 bits;
        returns (tree', build', [(lkey, rkey)], build_unique) with
        projections appended, or None when stats can't prove the pack
        is bijective (caller falls back to residual demotion)."""
        tree_schema = dict(tree.output_schema())
        build_schema = dict(build.output_schema())
        ranges = []
        for ci, cj in pairs:
            lt, rt = tree_schema[ci], build_schema[cj]
            if not (
                (lt.is_integer or lt.name == "date")
                and (rt.is_integer or rt.name == "date")
            ):
                return None
            cs_l = optimizer._column_stats(tree, ci, self.catalogs)
            cs_r = optimizer._column_stats(build, cj, self.catalogs)
            if (
                cs_l is None
                or cs_r is None
                or cs_l.min_value is None
                or cs_l.max_value is None
                or cs_r.min_value is None
                or cs_r.max_value is None
            ):
                return None
            lo = min(int(cs_l.min_value), int(cs_r.min_value))
            hi = max(int(cs_l.max_value), int(cs_r.max_value))
            ranges.append((lo, hi))
        widths = [max(hi - lo + 1, 1).bit_length() for lo, hi in ranges]
        if sum(widths) > 62:
            return None
        # build an equal value on equal composites: equal shifts/los on
        # both sides; NULL components null the whole key (never match)
        shifts = []
        s = 0
        for w in reversed(widths):
            shifts.append(s)
            s += w
        shifts = list(reversed(shifts))

        def packed_expr(schema, cols):
            total = None
            for (col, (lo, _hi), shift) in zip(cols, ranges, shifts):
                ref = E.Cast(
                    E.ColumnRef(col, schema[col]), T.BIGINT
                )
                term = E.Arithmetic(
                    "*",
                    E.Arithmetic(
                        "-", ref, E.Literal(lo, T.BIGINT), T.BIGINT
                    ),
                    E.Literal(1 << shift, T.BIGINT),
                    T.BIGINT,
                )
                total = (
                    term
                    if total is None
                    else E.Arithmetic("+", total, term, T.BIGINT)
                )
            return total

        unique = optimizer.is_build_unique(
            build, tuple(cj for _, cj in pairs), self.catalogs
        )
        lname, rname = self._fresh("packl"), self._fresh("packr")
        tree2 = N.ProjectNode(
            source=tree,
            projections=tuple(
                (n, E.ColumnRef(n, t)) for n, t in tree_schema.items()
            )
            + ((lname, packed_expr(tree_schema, [ci for ci, _ in pairs])),),
        )
        build2 = N.ProjectNode(
            source=build,
            projections=tuple(
                (n, E.ColumnRef(n, t)) for n, t in build_schema.items()
            )
            + ((rname, packed_expr(build_schema, [cj for _, cj in pairs])),),
        )
        return tree2, build2, [(lname, rname)], unique

    def _probe_key(self, node, scope, arg_ast):
        """Column name for a probe-side join key (project if not a bare
        column)."""
        e = self._lower(arg_ast, scope)
        if isinstance(e, E.ColumnRef):
            return node, scope, e.name
        name = self._fresh("key")
        schema = node.output_schema()
        projs = [
            (n, E.ColumnRef(n, t)) for n, t in schema.items()
        ] + [(name, e)]
        node = N.ProjectNode(node, tuple(projs))
        scope = Scope({**scope.columns, name: e.dtype}, scope.qualifiers, scope.parent)
        return node, scope, name

    def _apply_in_subquery(self, node, scope, a: ast.InSubquery, negate):
        if self._is_correlated(a.query, scope):
            # correlated IN rewrites to correlated EXISTS with the
            # membership as one more equality (reference: the
            # InPredicate -> quantified-comparison -> semi-join chain):
            #   x IN (select y from t where corr)
            #   == EXISTS (select 1 from t where corr and y = x)
            # NOT IN keeps its null-awareness requirement: a NULL x or
            # NULL y makes the anti join inexact, so reject it rather
            # than risk silent wrong rows.
            if negate:
                raise PlanningError(
                    "correlated NOT IN requires null-aware "
                    "three-valued semantics (unsupported)"
                )
            q = a.query
            if len(q.items) != 1 or q.group_by or q.having or q.distinct:
                raise PlanningError(
                    "unsupported correlated IN subquery shape"
                )
            item = q.items[0]
            inner_expr = item.expr
            # the rewrite moves a.arg INSIDE the subquery: sound only
            # when none of its column names resolve against the inner
            # relations (unqualified resolution prefers the inner
            # scope, which would silently change the comparison into
            # an inner self-equality — oracle-caught)
            _, inner_scope = self._plan_from(q.from_, None)
            shadowed = []

            def _check(n):
                if isinstance(n, ast.Ident):
                    try:
                        inner_scope.resolve(n.parts)
                        shadowed.append(n)
                    except PlanningError:
                        pass
                    return
                if not isinstance(n, ast.Node):
                    return
                for f_ in dataclasses.fields(n):
                    v = getattr(n, f_.name)
                    if isinstance(v, ast.Node):
                        _check(v)
                    elif isinstance(v, tuple):
                        for x in v:
                            if isinstance(x, ast.Node):
                                _check(x)

            _check(a.arg)
            if shadowed:
                raise PlanningError(
                    "correlated IN whose left side is shadowed by the "
                    f"subquery's relations ({shadowed[0]}) is not "
                    "supported (qualify the outer column)"
                )
            eq = ast.BinaryOp("=", inner_expr, a.arg)
            inner = ast.Select(
                items=(ast.SelectItem(ast.NumberLit("1"), None),),
                from_=q.from_,
                where=(
                    eq
                    if q.where is None
                    else ast.BinaryOp("and", q.where, eq)
                ),
                ctes=q.ctes,
            )
            return self._apply_exists(
                node, scope, ast.Exists(inner), False
            )
        sub_node, _, sub_names = self.plan_select(a.query, outer=None)
        if len(sub_names) != 1:
            raise PlanningError("IN subquery must return one column")
        node, scope, key = self._probe_key(node, scope, a.arg)
        if scope.columns[key].is_long_decimal:
            # semi/anti output has no build columns, so the kernel's
            # mixed long-decimal key cannot be residual-verified — a mix
            # collision would KEEP a wrong row. Inner joins stay exact
            # (residual limb equality); membership tests keep the gate.
            raise PlanningError(
                "IN/NOT IN on a long decimal (p>18) is not supported "
                "(documented deviation; cast to decimal(18,s))"
            )
        if negate:
            node = self._null_aware_prefilter(node, scope, a.query, key)
        node = N.JoinNode(
            left=node,
            right=sub_node,
            join_type="anti" if negate else "semi",
            left_keys=(key,),
            right_keys=(sub_names[0],),
            payload=(),
        )
        return node, scope

    def _not_in_state_param(self, query: ast.Select) -> E.Param:
        """Plan ``query`` once under a fresh param namespace and reduce
        it to ONE bound scalar classifying S for the null-aware NOT IN
        rewrite: 0 = S empty, 1 = S non-empty and null-free,
        2 = S contains a NULL (one subquery execution for both counts)."""
        saved = self.params
        self.params = []
        try:
            cnt_node, _, cnt_names = self.plan_select(query, outer=None)
            col = cnt_names[0]
            col_t = cnt_node.output_schema()[col]
            total = E.ColumnRef("$na_total", T.BIGINT)
            non_null = E.ColumnRef("$na_nonnull", T.BIGINT)
            zero = E.Literal(0, T.BIGINT)
            state = E.Case(
                whens=(
                    (E.Compare("=", total, zero), zero),
                    (
                        E.Compare("=", total, non_null),
                        E.Literal(1, T.BIGINT),
                    ),
                ),
                default=E.Literal(2, T.BIGINT),
                _dtype=T.BIGINT,
            )
            sub = Plan(
                root=N.ProjectNode(
                    source=N.AggregationNode(
                        source=cnt_node,
                        group_keys=(),
                        aggs=(
                            AggCall("count_star", None, "$na_total"),
                            AggCall(
                                "count",
                                E.ColumnRef(col, col_t),
                                "$na_nonnull",
                            ),
                        ),
                    ),
                    projections=(("$na_state", state),),
                ),
                params=self.params,
                output_names=("$na_state",),
            )
        finally:
            self.params = saved
        pid = self._param_counter[0]
        self._param_counter[0] += 1
        self.params.append((pid, sub))
        return E.Param(pid, T.BIGINT)

    def _null_aware_prefilter(self, node, scope, query: ast.Select, key):
        """Null-aware anti join (reference: the null-aware rewrite of
        NOT IN — SURVEY.md §2.1 "Logical planner" subquery rewrites; a
        plain anti join has NOT-EXISTS semantics and returns wrong
        answers on NULLs). SQL three-valued logic for ``x NOT IN (S)``:

          - S empty                  -> TRUE for every x (even NULL)
          - x NULL and S non-empty   -> UNKNOWN (row dropped)
          - S contains NULL          -> never TRUE (match -> FALSE,
                                        else UNKNOWN -> dropped)

        One bound scalar param (0 = S empty, 1 = null-free, 2 = has a
        NULL — computed from a single execution of S) turns this into a
        probe-side pre-filter: keep a probe row iff ``state = 0 OR
        (x IS NOT NULL AND state = 1)``; the anti join then decides
        membership for the surviving (non-null x, null-free S) cases,
        and an empty S makes the anti join keep everything."""
        state = self._not_in_state_param(query)
        probe_ref = E.ColumnRef(key, scope.columns[key])
        pred = E.Or(
            (
                E.Compare("=", state, E.Literal(0, T.BIGINT)),
                E.And(
                    (
                        E.IsNull(probe_ref, negate=True),
                        E.Compare("=", state, E.Literal(1, T.BIGINT)),
                    )
                ),
            )
        )
        return N.FilterNode(source=node, predicate=pred)

    def _apply_exists(self, node, scope, a: ast.Exists, negate):
        q = a.query
        corr_pairs, neq_pairs, residual_where = self._extract_correlation(
            q, scope, collect_neq=True
        )
        if not corr_pairs:
            raise PlanningError(
                "uncorrelated or non-equality-correlated EXISTS is not "
                "supported yet"
            )
        for _, outer_col in corr_pairs:
            if scope.columns[outer_col].is_long_decimal:
                # before the neq_pairs branch: BOTH decorrelation forms
                # end in a semi/anti join whose keys cannot
                # residual-verify the 128->64 key mix
                raise PlanningError(
                    "EXISTS correlated on a long decimal (p>18) is not "
                    "supported (documented deviation: semi-join keys "
                    "cannot residual-verify the 128->64 key mix)"
                )
        if neq_pairs:
            if len(neq_pairs) > 1:
                raise PlanningError(
                    "EXISTS with multiple inequality-correlated "
                    "conjuncts is not supported"
                )
            return self._apply_exists_neq(
                node, scope, q, corr_pairs, neq_pairs[0],
                residual_where, negate,
            )
        inner_cols = tuple(p[0] for p in corr_pairs)
        inner_sel = ast.Select(
            items=tuple(
                ast.SelectItem(ast.Ident((c,)), None) for c in inner_cols
            ),
            from_=q.from_,
            where=residual_where,
            ctes=q.ctes,
        )
        sub_node, _, sub_names = self.plan_select(inner_sel, outer=None)
        outer_keys = tuple(p[1] for p in corr_pairs)
        node = N.JoinNode(
            left=node,
            right=sub_node,
            join_type="anti" if negate else "semi",
            left_keys=outer_keys,
            right_keys=sub_names,
            payload=(),
        )
        return node, scope

    def _apply_exists_neq(
        self, node, scope, q, corr_pairs, neq_pair, residual_where, negate
    ):
        """Decorrelate ``EXISTS(inner.k = outer.k AND inner.c <> outer.c
        [AND pure-inner residual])`` by counting (the classic Q21
        rewrite; reference: ApplyNode correlated-EXISTS transformations):

            cnt_all(k)    = rows of inner per equality key with c NOT NULL
            cnt_self(k,c) = rows of inner per (key, c)
            EXISTS     <=> outer.c IS NOT NULL
                           AND coalesce(cnt_all,0)-coalesce(cnt_self,0) > 0
            NOT EXISTS <=> outer.c IS NULL
                           OR coalesce(cnt_all,0)-coalesce(cnt_self,0) = 0

        NULL semantics: an inner row with c NULL makes ``c <> outer.c``
        UNKNOWN (never satisfies EXISTS), so cnt_all counts ``count(c)``,
        not ``count(*)``; an outer row with c NULL makes every comparison
        UNKNOWN, so EXISTS is forced false (NOT EXISTS true) regardless
        of counts. Both lookups are left joins against grouped (hence
        unique-keyed) builds — TPU-friendly: two hash joins + a filter,
        no per-row subquery."""
        inner_eq = [p[0] for p in corr_pairs]
        outer_eq = [p[1] for p in corr_pairs]
        neq_inner, neq_outer = neq_pair

        def grouped_count(group_cols, count_col):
            aliases = [self._fresh("ckey") for _ in group_cols]
            cnt = self._fresh("cnt")
            count_args = (
                (ast.Ident((count_col,)),) if count_col is not None else ()
            )
            sel = ast.Select(
                items=tuple(
                    ast.SelectItem(ast.Ident((c,)), alias)
                    for c, alias in zip(group_cols, aliases)
                )
                + (
                    ast.SelectItem(
                        ast.FuncCall("count", count_args), cnt.lstrip("$")
                    ),
                ),
                from_=q.from_,
                where=residual_where,
                group_by=tuple(ast.Ident((c,)) for c in group_cols),
                ctes=q.ctes,
            )
            sub_node, _, sub_names = self.plan_select(sel, outer=None)
            return sub_node, sub_names[:-1], sub_names[-1]

        all_node, all_keys, cnt_all = grouped_count(inner_eq, neq_inner)
        self_node, self_keys, cnt_self = grouped_count(
            inner_eq + [neq_inner], None
        )

        node = N.JoinNode(
            left=node,
            right=all_node,
            join_type="left",
            left_keys=tuple(outer_eq),
            right_keys=tuple(all_keys),
            payload=(cnt_all,),
            build_unique=True,  # grouped by the join keys
        )
        node = N.JoinNode(
            left=node,
            right=self_node,
            join_type="left",
            left_keys=tuple(outer_eq) + (neq_outer,),
            right_keys=tuple(self_keys),
            payload=(cnt_self,),
            build_unique=True,
        )
        sch = node.output_schema()
        zero = E.Literal(0, T.BIGINT)
        diff = E.arith(
            "-",
            E.Coalesce((E.ColumnRef(cnt_all, sch[cnt_all]), zero), T.BIGINT),
            E.Coalesce(
                (E.ColumnRef(cnt_self, sch[cnt_self]), zero), T.BIGINT
            ),
        )
        outer_c = E.ColumnRef(neq_outer, sch[neq_outer])
        if negate:  # NOT EXISTS
            pred: E.Expr = E.Or(
                (E.IsNull(outer_c), E.Compare("=", diff, zero))
            )
        else:  # EXISTS
            pred = E.And(
                (
                    E.IsNull(outer_c, negate=True),
                    E.Compare(">", diff, zero),
                )
            )
        node = N.FilterNode(node, pred)
        # the helper count columns are internal: restore the outer scope
        return node, scope

    def _apply_correlated_scalar(self, node, scope, cmp: ast.BinaryOp):
        (sub,) = (
            s
            for s in _find_scalar_subqueries(cmp)
            if self._is_correlated(s.query, scope)
        )
        q = sub.query
        corr_pairs, residual_where = self._extract_correlation(q, scope)
        if not corr_pairs:
            raise PlanningError(
                "correlated scalar subquery requires equality correlation"
            )
        if len(q.items) != 1 or q.group_by or q.having:
            raise PlanningError(
                "unsupported correlated scalar subquery shape"
            )
        inner_keys = tuple(p[0] for p in corr_pairs)
        outer_keys = tuple(p[1] for p in corr_pairs)
        val_name = self._fresh("scalar")
        key_aliases = [self._fresh("ckey") for _ in inner_keys]
        inner_sel = ast.Select(
            items=tuple(
                ast.SelectItem(ast.Ident((c,)), alias)
                for c, alias in zip(inner_keys, key_aliases)
            )
            + (ast.SelectItem(q.items[0].expr, val_name.lstrip("$")),),
            from_=q.from_,
            where=residual_where,
            group_by=tuple(ast.Ident((c,)) for c in inner_keys),
            ctes=q.ctes,
        )
        sub_node, _, sub_names = self.plan_select(inner_sel, outer=None)
        val_col = sub_names[-1]
        node = N.JoinNode(
            left=node,
            right=sub_node,
            join_type="inner",
            left_keys=outer_keys,
            right_keys=tuple(sub_names[: len(inner_keys)]),
            payload=(val_col,),
            build_unique=True,  # grouped by the join keys
        )
        sch = node.output_schema()
        scope = Scope(dict(sch), scope.qualifiers, scope.parent)
        # lower the WHOLE comparison with the subquery ast mapped to
        # the joined value column (agg_map doubles as an ast->column
        # substitution), so arithmetic around the subquery just works
        pred = self._lower(cmp, scope, agg_map={sub: val_col})
        return N.FilterNode(node, pred), scope

    def _extract_correlation(
        self,
        q: ast.Select,
        outer_scope: Scope,
        collect_neq: bool = False,
    ):
        """Split the inner WHERE into (inner_col = outer_col) correlation
        pairs and the residual. Returns ([(inner_col, outer_col)], where)
        — or, with ``collect_neq``, a 3-tuple whose middle element lists
        (inner_col <> outer_col) pairs (Q21's correlation shape)."""
        inner_node_probe, inner_scope = self._plan_from(q.from_, None)
        pairs: List[Tuple[str, str]] = []
        neq_pairs: List[Tuple[str, str]] = []
        rest: List[ast.Node] = []
        for c in _split_conjuncts(q.where) if q.where is not None else []:
            pair = None
            is_eq = True
            if (
                isinstance(c, ast.BinaryOp)
                and (
                    c.op == "="
                    or (collect_neq and c.op in ("<>", "!="))
                )
                and isinstance(c.left, ast.Ident)
                and isinstance(c.right, ast.Ident)
            ):
                is_eq = c.op == "="
                for inner_ast, outer_ast in (
                    (c.left, c.right),
                    (c.right, c.left),
                ):
                    try:
                        ic, _, i_outer = inner_scope.resolve(inner_ast.parts)
                        if i_outer:
                            continue
                    except PlanningError:
                        continue
                    try:
                        inner_scope.resolve(outer_ast.parts)
                        continue  # both resolve inner: a plain conjunct
                    except PlanningError:
                        pass
                    try:
                        oc, _, _ = outer_scope.resolve(outer_ast.parts)
                    except PlanningError:
                        continue
                    pair = (ic, oc)
                    break
            if pair and is_eq:
                pairs.append(pair)
            elif pair:
                neq_pairs.append(pair)
            else:
                rest.append(c)
        where = None
        if rest:
            where = rest[0]
            for c in rest[1:]:
                where = ast.BinaryOp("and", where, c)
        if collect_neq:
            return pairs, neq_pairs, where
        return pairs, where

    # --------------------------------------------------------- aggregation

    def _contains_agg(self, e: ast.Node) -> bool:
        if isinstance(e, ast.FuncCall):
            if e.window is None and functions.is_aggregate(e.name):
                return True
        return any(
            self._contains_agg(c) for c in _ast_children(e)
        )

    def _contains_window(self, e: ast.Node) -> bool:
        if isinstance(e, ast.FuncCall) and e.window is not None:
            return True
        return any(self._contains_window(c) for c in _ast_children(e))

    def _collect_aggs(self, e: ast.Node, out: List[ast.FuncCall]):
        if (
            isinstance(e, ast.FuncCall)
            and e.window is None
            and functions.is_aggregate(e.name)
        ):
            if e not in out:
                out.append(e)
            return
        for c in _ast_children(e):
            self._collect_aggs(c, out)

    def _plan_aggregation(self, node, scope, sel: ast.Select):
        node = self._finalize_pool(node, scope)
        agg_calls: List[ast.FuncCall] = []
        for it in sel.items:
            if not isinstance(it.expr, ast.Star):
                self._collect_aggs(it.expr, agg_calls)
        if sel.having is not None:
            self._collect_aggs(sel.having, agg_calls)
        for si in sel.order_by:
            self._collect_aggs(si.expr, agg_calls)

        agg_map: Dict[ast.Node, str] = {}
        group_keys: List[Tuple[str, E.Expr]] = []
        for g in sel.group_by:
            if isinstance(g, ast.NumberLit):
                # GROUP BY ordinal (reference: GROUP BY 1 resolves to
                # the first select item)
                try:
                    idx = int(g.text) - 1
                except ValueError:
                    raise PlanningError(
                        f"GROUP BY position must be an integer, got "
                        f"{g.text}"
                    ) from None
                if not (0 <= idx < len(sel.items)) or isinstance(
                    sel.items[idx].expr, ast.Star
                ):
                    raise PlanningError(
                        f"GROUP BY position {g.text} out of range"
                    )
                g = sel.items[idx].expr
            e = self._lower(g, scope)
            if e.dtype.is_nested:
                raise PlanningError(
                    f"GROUP BY a {e.dtype.name} column is not supported"
                )
            if isinstance(e, E.ColumnRef):
                group_keys.append((e.name, e))
            else:
                # expression key: select items / HAVING / ORDER BY
                # re-lowering the same AST resolve to the key column
                name = self._fresh("key")
                group_keys.append((name, e))
                agg_map[g] = name
        distinct_aggs = [
            a
            for a in agg_calls
            if a.distinct or a.name == "approx_distinct"
        ]
        plain_aggs = [a for a in agg_calls if a not in distinct_aggs]
        if distinct_aggs:
            for a in distinct_aggs:
                if a.name not in ("count", "approx_distinct"):
                    raise PlanningError(
                        f"{a.name}(DISTINCT x) is not supported "
                        "(count/approx_distinct only)"
                    )
            needs_stitch = bool(plain_aggs) or len(distinct_aggs) > 1
            if needs_stitch and len(group_keys) > 2:
                raise PlanningError(
                    "multiple/mixed DISTINCT aggregates support at "
                    "most 2 group keys (stitch-join key width)"
                )
            if needs_stitch and len(group_keys) == 2 and any(
                e.dtype.np_dtype.itemsize > 4 for _, e in group_keys
            ):
                # the stitch join packs both keys into one int64
                # (ops.join.pack_keys) — fail at plan time, not runtime
                raise PlanningError(
                    "multiple/mixed DISTINCT aggregates require "
                    "32-bit group keys when there are two"
                )
            # each DISTINCT agg gets its own two-level tree over the
            # SAME source (reference: MarkDistinct feeding one
            # HashAggregation); multiple trees stitch per group via
            # unique-build joins (identical group sets by
            # construction), or single-row broadcasts when global
            parts: List[Tuple[N.PlanNode, str]] = []
            for a in distinct_aggs:
                arg = self._lower(a.args[0], scope)
                dcol = self._fresh("dist")
                pre = N.AggregationNode(
                    source=node,
                    group_keys=tuple(group_keys) + ((dcol, arg),),
                    aggs=(),
                    max_groups=self._agg_bucket(node),
                )
                out_name = self._fresh("agg")
                post = N.AggregationNode(
                    source=pre,
                    group_keys=tuple(
                        (n, E.ColumnRef(n, e.dtype))
                        for n, e in group_keys
                    ),
                    aggs=(
                        AggCall(
                            "count",
                            E.ColumnRef(dcol, arg.dtype),
                            out_name,
                        ),
                    ),
                    max_groups=self._agg_bucket(node),
                )
                agg_map[a] = out_name
                parts.append((post, out_name))
            if plain_aggs:
                plain_node, agg_map2 = self._plain_agg_node(
                    node, group_keys, plain_aggs, scope
                )
                agg_map.update(agg_map2)
                stitched: N.PlanNode = plain_node
                rest = parts
            else:
                stitched = parts[0][0]
                rest = parts[1:]
            for post, out_name in rest:
                if group_keys:
                    stitched = N.JoinNode(
                        left=stitched,
                        right=post,
                        join_type="inner",
                        left_keys=tuple(n for n, _ in group_keys),
                        right_keys=tuple(n for n, _ in group_keys),
                        payload=(out_name,),
                        build_unique=True,
                    )
                else:
                    stitched = N.CrossJoinNode(
                        left=stitched, right=post
                    )
            out_scope = self._post_agg_scope(stitched, scope)
            if sel.having is not None:
                pred = self._lower(
                    sel.having, out_scope, agg_map=agg_map
                )
                stitched = N.FilterNode(stitched, pred)
            return stitched, out_scope, agg_map

        agg_node, agg_map2 = self._plain_agg_node(
            node, group_keys, agg_calls, scope
        )
        agg_map.update(agg_map2)
        out_scope = self._post_agg_scope(agg_node, scope)
        if sel.having is not None:
            pred = self._lower(sel.having, out_scope, agg_map=agg_map)
            agg_node = N.FilterNode(agg_node, pred)
        return agg_node, out_scope, agg_map

    def _plain_agg_node(self, node, group_keys, agg_calls, scope):
        """Lower aggregate calls through the function registry
        (functions.AGGREGATE — the reference's FunctionAndTypeManager
        resolution seam). Kernel aggregates become AggCalls directly;
        COMPOSED aggregates (avg, variance family, corr, ... —
        functions.ComposedAgg) become their primitive mergeable state
        AggCalls plus a finisher projection stacked on the aggregation
        (the reference's accumulator/output split), so the kernel and
        the distributed partial/final rewrite only ever see
        self-mergeable primitives."""
        aggs: List[AggCall] = []
        agg_map: Dict[ast.Node, str] = {}
        #: ordered final outputs: (name, finish_expr|None, dtype|None)
        outputs: List[Tuple[str, Optional[E.Expr]]] = []
        any_composed = False
        for a in agg_calls:
            out_name = self._fresh("agg")
            if a.name == "count" and not a.args:
                aggs.append(AggCall("count_star", None, out_name))
                outputs.append((out_name, None))
                agg_map[a] = out_name
                continue
            args = [self._lower(x, scope) for x in a.args]
            for arg in args:
                # count ignores the value; checksum hashes the (hi, lo)
                # limb pair directly (expr.ValueHash long-decimal path)
                if arg.dtype.is_long_decimal and a.name not in (
                    "count", "checksum",
                ):
                    raise PlanningError(
                        f"{a.name}() over {arg.dtype} is not supported: "
                        "long-decimal accumulators are a documented "
                        "deviation (no benchmark config aggregates "
                        ">18-digit decimals) — cast to decimal(18,s) "
                        "or double to aggregate"
                    )
            try:
                low = functions.lower_aggregate(a.name, args)
            except functions.FunctionError as err:
                raise PlanningError(str(err)) from None
            if isinstance(low, functions.KernelAgg):
                aggs.append(
                    AggCall(
                        low.func, low.arg, out_name,
                        arg2=low.arg2, param=low.param,
                    )
                )
                outputs.append((out_name, None))
            else:  # ComposedAgg: primitive states + finisher expr
                any_composed = True
                refs: Dict[str, E.Expr] = {}
                for suffix, prim, sexpr in low.states:
                    sname = f"{out_name}${suffix}"
                    aggs.append(AggCall(prim, sexpr, sname))
                    refs[suffix] = E.ColumnRef(
                        sname, functions.agg_state_type(prim, sexpr)
                    )
                outputs.append((out_name, (low.finish(refs), low.dtype)))
            agg_map[a] = out_name
        agg_node: N.PlanNode = N.AggregationNode(
            source=node,
            group_keys=tuple(group_keys),
            aggs=tuple(aggs),
            max_groups=self._agg_bucket(node) if group_keys else 1,
        )
        if any_composed:
            projs: List[Tuple[str, E.Expr]] = [
                (n, E.ColumnRef(n, e.dtype)) for n, e in group_keys
            ]
            for name, fin in outputs:
                if fin is None:
                    dt = dict(agg_node.output_schema())[name]
                    projs.append((name, E.ColumnRef(name, dt)))
                else:
                    fexpr, fdtype = fin
                    # the registry's declared dtype is the contract;
                    # coerce a mismatched finisher rather than letting
                    # the drift ship silently
                    if fexpr.dtype != fdtype:
                        fexpr = E.Cast(fexpr, fdtype)
                    projs.append((name, fexpr))
            agg_node = N.ProjectNode(
                source=agg_node, projections=tuple(projs)
            )
        return agg_node, agg_map

    def _post_agg_scope(self, agg_node, scope) -> Scope:
        """Scope after aggregation: only grouped/aggregated columns
        survive, but alias qualifiers must keep resolving for the ones
        that do (SELECT ad1.ca_city ... GROUP BY ad1.ca_city)."""
        out_cols = dict(agg_node.output_schema())
        quals = {
            q: {vis: i for vis, i in m.items() if i in out_cols}
            for q, m in scope.qualifiers.items()
        }
        quals = {q: m for q, m in quals.items() if m}
        return Scope(out_cols, quals, scope.parent)

    def _agg_bucket(self, node) -> int:
        est = optimizer.estimate_rows(node, self.catalogs)
        return bucket_capacity(max(int(est * 0.5) + 1024, 1024))

    # ------------------------------------------------------------- windows

    def _plan_windows(self, node, scope, sel: ast.Select, agg_map=None):
        # runs AFTER aggregation: window args and partition/order keys
        # may reference aggregate results (reference: Q98's
        # sum(sum(x)) over (partition by ...) — a window over the
        # grouped output), resolved through agg_map like select items
        node = self._finalize_pool(node, scope)
        lower_w = lambda x: self._lower(x, scope, agg_map=agg_map)  # noqa: E731
        calls: List[ast.FuncCall] = []

        def collect(e):
            if isinstance(e, ast.FuncCall) and e.window is not None:
                if e not in calls:
                    calls.append(e)
                return
            for c in _ast_children(e):
                collect(c)

        for it in sel.items:
            if not isinstance(it.expr, ast.Star):
                collect(it.expr)
        win_map: Dict[ast.Node, str] = {}
        by_spec: Dict[ast.Over, List[ast.FuncCall]] = {}
        for c in calls:
            by_spec.setdefault(c.window, []).append(c)
        for spec, fns in by_spec.items():
            pby = tuple(lower_w(p) for p in spec.partition_by)
            oby = tuple(
                SortKey(
                    lower_w(si.expr), si.descending, si.nulls_first
                )
                for si in spec.order_by
            )
            wcalls = []
            for f in fns:
                out_name = self._fresh("win")
                wf = functions.WINDOW.get(f.name)
                if wf is None:
                    raise PlanningError(
                        f"{f.name}() is not a window function"
                    )
                if wf.kind == "rank":
                    if f.args:
                        raise PlanningError(
                            f"{f.name}() takes no arguments"
                        )
                    wcalls.append(WindowCall(f.name, None, out_name))
                elif f.name == "count" and not f.args:
                    wcalls.append(WindowCall("count", None, out_name))
                elif wf.kind == "ntile":
                    n = self._const_int(f.args[0], "ntile bucket count")
                    wcalls.append(
                        WindowCall("ntile", None, out_name, offset=n)
                    )
                elif f.name == "nth_value":
                    if len(f.args) != 2:
                        raise PlanningError(
                            "nth_value() takes two arguments"
                        )
                    arg = lower_w(f.args[0])
                    n = self._const_int(f.args[1], "nth_value offset")
                    if n < 1:
                        raise PlanningError(
                            "nth_value offset must be >= 1"
                        )
                    wcalls.append(
                        WindowCall("nth_value", arg, out_name, offset=n)
                    )
                elif wf.kind == "nav":
                    arg = lower_w(f.args[0])
                    off = (
                        self._const_int(f.args[1], f"{f.name} offset")
                        if len(f.args) > 1
                        else 1
                    )
                    default = None
                    if len(f.args) > 2:
                        de = lower_w(f.args[2])
                        if not isinstance(de, E.Literal):
                            raise PlanningError(
                                f"{f.name} default must be a constant"
                            )
                        if de.dtype.is_string or arg.dtype.is_string:
                            # a string default needs dictionary
                            # resolution against the arg column
                            raise PlanningError(
                                f"{f.name} string defaults are not "
                                "supported yet"
                            )
                        # carry the literal as an Expr (cast to the arg
                        # type) so unit/scale handling stays in expr
                        default = (
                            de
                            if de.dtype == arg.dtype
                            else E.Cast(de, arg.dtype)
                        )
                    wcalls.append(
                        WindowCall(
                            f.name, arg, out_name,
                            offset=off, default=default,
                        )
                    )
                else:
                    # "value" (first_value/last_value) and "agg" kinds:
                    # one value argument over the frame
                    if not f.args:
                        raise PlanningError(
                            f"{f.name}() requires an argument"
                        )
                    arg = lower_w(f.args[0])
                    wcalls.append(
                        WindowCall(
                            f.name, arg, out_name,
                            frame=spec.frame or "range",
                        )
                    )
                win_map[f] = out_name
            node = N.WindowNode(node, pby, oby, tuple(wcalls))
        scope = Scope(dict(node.output_schema()), scope.qualifiers, scope.parent)
        return node, scope, win_map

    # ----------------------------------------------------- expr lowering

    def _lower_order_key(self, e, scope, projections, agg_map, win_map):
        """ORDER BY resolves output aliases first, then source scope.
        Returns an alias name (str) or a lowered Expr."""
        if isinstance(e, ast.Ident) and len(e.parts) == 1:
            for n, _ in projections:
                if n == e.parts[0]:
                    return n
        if isinstance(e, ast.NumberLit):  # ORDER BY ordinal
            idx = int(e.text) - 1
            if 0 <= idx < len(projections):
                return projections[idx][0]
            raise PlanningError(f"ORDER BY position {e.text} out of range")
        # output aliases may appear INSIDE order-key expressions
        # (Q36-class `order by case when lochierarchy = 0 ...`): lower
        # with the projection exprs as an Ident fallback
        return self._lower(
            e, scope, agg_map=agg_map, win_map=win_map,
            alias_map=dict(projections),
        )

    def _lower(
        self, e: ast.Node, scope: Scope, agg_map=None, win_map=None,
        alias_map=None,
    ) -> E.Expr:
        agg_map = agg_map or {}
        win_map = win_map or {}
        lower = lambda x: self._lower(  # noqa: E731
            x, scope, agg_map, win_map, alias_map
        )

        if e in agg_map:
            name = agg_map[e]
            return E.ColumnRef(name, scope.columns[name])
        if e in win_map:
            name = win_map[e]
            return E.ColumnRef(name, scope.columns[name])

        if isinstance(e, ast.Ident):
            try:
                name, dtype, is_outer = scope.resolve(e.parts)
            except PlanningError:
                # output-alias fallback (ORDER BY keys referencing
                # select aliases inside expressions)
                if (
                    alias_map
                    and len(e.parts) == 1
                    and e.parts[0] in alias_map
                ):
                    return alias_map[e.parts[0]]
                # row field access: the trailing part may be a field of
                # a ROW column (reference: DereferenceExpression)
                if len(e.parts) < 2:
                    raise
                base, dtype, is_outer = scope.resolve(e.parts[:-1])
                if not dtype.is_row:
                    raise
                field = e.parts[-1]
                try:
                    fi = dtype.field_index(field)
                except KeyError:
                    raise PlanningError(
                        f"row type {dtype} has no field {field}"
                    ) from None
                if is_outer:
                    raise PlanningError(
                        f"correlated reference {e} outside a supported "
                        "decorrelation pattern"
                    )
                return E.RowFieldAccess(
                    E.ColumnRef(base, dtype), field, dtype.fields[fi][1]
                )
            if is_outer:
                raise PlanningError(
                    f"correlated reference {e} outside a supported "
                    "decorrelation pattern"
                )
            return E.ColumnRef(name, dtype)
        if isinstance(e, ast.BoundParam):
            # canonicalized literal (plan/canonical.py): lower the
            # carried literal for its TYPE only — the value enters the
            # compiled program as a runtime parameter, never a constant,
            # which is exactly what makes the planned form reusable
            # across literal variants
            base = lower(e.lit)
            return E.RuntimeParam(e.ordinal, base.dtype)
        if isinstance(e, ast.NumberLit):
            return _number_literal(e.text)
        if isinstance(e, ast.StringLit):
            return E.Literal(e.value, T.VARCHAR)
        if isinstance(e, ast.NullLit):
            return E.Literal(None, T.BIGINT)
        if isinstance(e, ast.BoolLit):
            return E.Literal(e.value, T.BOOLEAN)
        if isinstance(e, ast.DateLit):
            return E.Literal(_parse_date(e.value), T.DATE)
        if isinstance(e, ast.IntervalLit):
            raise PlanningError(
                "interval literal outside date +/- interval context"
            )
        if isinstance(e, ast.UnaryOp):
            if e.op == "not":
                return E.Not(lower(e.arg))
            arg = lower(e.arg)
            if isinstance(arg, E.Literal) and arg.value is not None:
                return E.Literal(-arg.value, arg.dtype)
            return E.Negate(arg)
        if isinstance(e, ast.BinaryOp):
            if e.op == "and":
                return E.And((lower(e.left), lower(e.right)))
            if e.op == "or":
                return E.Or((lower(e.left), lower(e.right)))
            if e.op in ("=", "<>", "!=", "<", "<=", ">", ">="):
                return E.Compare(e.op, lower(e.left), lower(e.right))
            if e.op in ("+", "-"):
                # date +/- interval
                for a, b, flip in ((e.left, e.right, False), (e.right, e.left, True)):
                    if isinstance(b, ast.IntervalLit):
                        return self._date_interval(
                            lower(a), b, e.op, flip
                        )
            if e.op in ("+", "-", "*", "/", "%"):
                return E.arith(e.op, lower(e.left), lower(e.right))
            raise PlanningError(f"unsupported operator {e.op}")
        if isinstance(e, ast.CaseExpr):
            whens = []
            if e.operand is not None:
                op_l = lower(e.operand)
                for c, v in e.whens:
                    whens.append(
                        (E.Compare("=", op_l, lower(c)), lower(v))
                    )
            else:
                whens = [(lower(c), lower(v)) for c, v in e.whens]
            default = lower(e.default) if e.default is not None else None

            def _is_null_lit(x):
                return isinstance(x, E.Literal) and x.value is None

            # NULL-literal branches don't vote on the result type
            # (reference UNKNOWN coercion): `then 'label' else null`
            # stays varchar
            rtypes = [
                v.dtype for _, v in whens if not _is_null_lit(v)
            ]
            if default is not None and not _is_null_lit(default):
                rtypes.append(default.dtype)
            if not rtypes:
                rtypes = [T.BIGINT]
            rt = rtypes[0]
            for t in rtypes[1:]:
                rt = T.common_super_type(rt, t)
            whens = [
                (c, E.Literal(None, rt) if _is_null_lit(v) else v)
                for c, v in whens
            ]
            if default is not None and _is_null_lit(default):
                default = E.Literal(None, rt)
            return E.Case(tuple(whens), default, rt)
        if isinstance(e, ast.CastExpr):
            return E.Cast(lower(e.arg), T.parse_type(e.type_name))
        if isinstance(e, ast.BetweenExpr):
            return E.Between(
                lower(e.arg), lower(e.low), lower(e.high), e.negate
            )
        if isinstance(e, ast.InList):
            arg = lower(e.arg)
            vals = []
            exprs = []
            for v in e.values:
                lv = lower(v)
                lv = _fold_constant(lv)
                if not isinstance(lv, E.Literal):
                    exprs.append(lv)
                    continue
                if not arg.dtype.is_string and lv.dtype != arg.dtype:
                    lv = _coerce_literal(lv, arg.dtype)
                vals.append(lv)
            if exprs:
                # non-constant members (x IN (a, col+1, ...)): the
                # list form keeps the literals, the rest become OR'd
                # equalities (reference: InPredicate rewrite)
                terms = [
                    E.Compare("=", arg, x) for x in exprs
                ]
                if vals:
                    terms.append(
                        E.InList(arg, tuple(vals), False)
                    )
                disj = (
                    terms[0] if len(terms) == 1 else E.Or(tuple(terms))
                )
                if e.negate:
                    return E.Not(disj)
                return disj
            return E.InList(arg, tuple(vals), e.negate)
        if isinstance(e, ast.LikeExpr):
            pat = lower(e.pattern)
            if not isinstance(pat, E.Literal):
                raise PlanningError("LIKE pattern must be a literal")
            return E.Like(lower(e.arg), pat.value, e.negate)
        if isinstance(e, ast.IsNullExpr):
            return E.IsNull(lower(e.arg), e.negate)
        if isinstance(e, ast.ExtractExpr):
            return E.Extract(e.field, lower(e.arg))
        if isinstance(e, ast.ScalarSubquery):
            saved = list(self.params)
            try:
                sub = self.plan(e.query)
            except PlanningError as err:
                self.params = saved
                raise PlanningError(
                    f"scalar subquery planning failed ({err}); if the "
                    "subquery is correlated, only conjunct-level "
                    "equality-correlated comparisons are supported"
                ) from err
            if len(sub.output_names) != 1:
                raise PlanningError("scalar subquery must return one column")
            dtype = sub.root.output_schema()[sub.output_names[0]]
            pid = self._param_counter[0]
            self._param_counter[0] += 1
            self.params = saved
            self.params.append((pid, sub))
            return E.Param(pid, dtype)
        if isinstance(e, ast.FuncCall):
            if e.window is not None:
                raise PlanningError(
                    "window function in an unsupported position"
                )
            if functions.is_aggregate(e.name):
                raise PlanningError(
                    f"aggregate {e.name}() in an unsupported position"
                )
            if e.name in ("cardinality", "element_at", "contains"):
                # array functions take raw ArrayLit ASTs, not lowered
                # exprs (arrays are trace-time expression lists)
                return self._lower_array_func(e, lower)
            from presto_tpu import functions as F

            try:
                return F.lower_scalar(
                    e.name, [lower(a) for a in e.args]
                )
            except F.FunctionError as err:
                raise PlanningError(str(err)) from None
        if isinstance(e, ast.ArrayLit):
            raise PlanningError(
                "ARRAY[...] is supported under UNNEST, cardinality, "
                "element_at, contains, and the [] subscript (arrays are "
                "trace-time expression lists; no physical array columns)"
            )
        raise PlanningError(f"cannot lower {type(e).__name__}")

    def _map_subscript_key(self, key: E.Expr, kt) -> E.Expr:
        """Normalize a map-subscript key into the key child's VALUE
        DOMAIN so the kernel's raw device-representation compare is
        exact (unscaled decimals would otherwise compare 10 vs 1 for
        the same value; fractional doubles would truncate onto spurious
        integer matches)."""
        if kt.is_long_decimal:
            raise PlanningError(
                "long-decimal map keys are not supported"
            )
        if key.dtype == kt:
            return key
        if kt.is_integer and key.dtype.is_integer:
            return key  # widths widen exactly in the kernel
        if (
            kt.is_integer
            and key.dtype.is_decimal
            and not key.dtype.is_long_decimal
            and isinstance(key, E.Literal)
            and key.value is not None
        ):
            # integer-valued decimal literal (m[1.0]): fold to the
            # integer it equals; fractional literals match no key
            unscaled, s = int(key.value), key.dtype.scale
            if unscaled % (10 ** s) == 0:
                return E.Literal(unscaled // (10 ** s), kt)
            return E.Literal(None, kt)  # x.5 = no integer key
        if kt.name in ("double", "real") and (
            key.dtype.is_integer or key.dtype.name in ("double", "real")
        ):
            return E.Cast(key, kt)
        if kt.is_decimal and (
            key.dtype.is_integer
            or (
                key.dtype.is_decimal
                and not key.dtype.is_long_decimal
                and key.dtype.scale <= kt.scale
            )
        ):
            # exact rescale into kt's unscaled domain
            return E.Cast(key, kt)
        if kt.is_string and key.dtype.is_string:
            return key
        raise PlanningError(
            f"map key type {kt} does not admit a subscript of type "
            f"{key.dtype} (exact-equality domains only)"
        )

    def _lower_array_func(self, e: ast.FuncCall, lower):
        """Array functions over ARRAY[...] constructors. Arrays are
        trace-time expression lists (see N.UnnestNode), so these fold
        into ordinary scalar expressions:
          cardinality(ARRAY[..k..])      -> literal k
          element_at(arr, i) / arr[i]    -> the i-th element (literal i)
                                            or a CASE chain (column i);
                                            out-of-range -> NULL (Presto
                                            element_at semantics)
          contains(arr, x)               -> OR of equality comparisons
                                            (3VL OR gives Presto's
                                            true/NULL/false behavior)
        """
        if e.args and not isinstance(e.args[0], ast.ArrayLit):
            # physical array COLUMN (reference: ArrayType columns):
            # cardinality/element_at lower to offsets-based kernels
            arg0 = lower(e.args[0])
            if arg0.dtype.is_array:
                if e.name == "cardinality":
                    if len(e.args) != 1:
                        raise PlanningError(
                            "cardinality() takes one argument"
                        )
                    return E.ArrayLength(arg0)
                if e.name == "element_at":
                    if len(e.args) != 2:
                        raise PlanningError(
                            "element_at() takes two arguments"
                        )
                    return E.ArraySubscript(arg0, lower(e.args[1]))
                raise PlanningError(
                    f"{e.name}() over physical array columns is not "
                    "supported (cardinality/element_at/unnest are)"
                )
            if arg0.dtype.is_map:
                if e.name == "cardinality":
                    return E.ArrayLength(arg0)
                if e.name == "element_at":
                    if len(e.args) != 2:
                        raise PlanningError(
                            "element_at() takes two arguments"
                        )
                    key = lower(e.args[1])
                    kt = arg0.dtype.key
                    key = self._map_subscript_key(key, kt)
                    return E.MapSubscript(arg0, key)
                raise PlanningError(
                    f"{e.name}() over map columns is not supported "
                    "(cardinality/element_at/the [] subscript are)"
                )
        if not e.args or not isinstance(e.args[0], ast.ArrayLit):
            raise PlanningError(
                f"{e.name}() requires an ARRAY[...] constructor argument"
            )
        items = e.args[0].items
        if e.name == "cardinality":
            if len(e.args) != 1:
                raise PlanningError("cardinality() takes one argument")
            return E.Literal(len(items), T.BIGINT)
        if not items:
            raise PlanningError(f"{e.name}() over empty ARRAY[]")
        els = [lower(it) for it in items]
        ct = els[0].dtype
        for el in els[1:]:
            ct = T.common_super_type(ct, el.dtype)
        els = [el if el.dtype == ct else E.Cast(el, ct) for el in els]
        if len(e.args) != 2:
            raise PlanningError(f"{e.name}() takes two arguments")
        arg = lower(e.args[1])
        if e.name == "element_at":
            k = len(els)
            if isinstance(arg, E.Literal):
                i = int(arg.value) if arg.value is not None else 0
                if 1 <= i <= k:
                    return els[i - 1]
                if -k <= i <= -1:  # Presto: negative = from the end
                    return els[k + i]
                return E.Literal(None, ct)  # out of range -> NULL
            whens = tuple(
                (
                    E.Compare("=", arg, E.Literal(i + 1, T.BIGINT)),
                    el,
                )
                for i, el in enumerate(els)
            ) + tuple(
                (
                    E.Compare("=", arg, E.Literal(i - k, T.BIGINT)),
                    el,
                )
                for i, el in enumerate(els)
            )
            return E.Case(whens, E.Literal(None, ct), ct)
        # contains(arr, x): 3VL OR over equality with each element
        if not arg.dtype.is_string and arg.dtype != ct:
            arg = E.Cast(arg, ct)
        cmps = tuple(E.Compare("=", arg, el) for el in els)
        return cmps[0] if len(cmps) == 1 else E.Or(cmps)

    def _date_interval(self, date_expr, iv: ast.IntervalLit, op, flip):
        if flip and op == "-":
            raise PlanningError("interval - date is invalid")
        n = int(iv.value) * (-1 if iv.negative else 1)
        if op == "-":
            n = -n
        if iv.unit == "day":
            if isinstance(date_expr, E.Literal):
                return E.Literal(date_expr.value + n, T.DATE)
            return E.Arithmetic("+", date_expr, E.Literal(n, T.BIGINT), T.DATE)
        # month/year shifts: constant-fold only (TPC-H always does)
        if not isinstance(date_expr, E.Literal):
            raise PlanningError(
                f"interval '{iv.value}' {iv.unit} requires a literal date"
            )
        months = n * (12 if iv.unit == "year" else 1)
        d = datetime.date(1970, 1, 1) + datetime.timedelta(
            days=int(date_expr.value)
        )
        total = d.year * 12 + (d.month - 1) + months
        y, m = divmod(total, 12)
        import calendar

        day = min(d.day, calendar.monthrange(y, m + 1)[1])
        nd = datetime.date(y, m + 1, day)
        return E.Literal(
            (nd - datetime.date(1970, 1, 1)).days, T.DATE
        )


def _ast_children(e: ast.Node):
    if not dataclasses.is_dataclass(e):
        return
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, ast.Node) and not isinstance(v, ast.Select):
            yield v
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, ast.Node) and not isinstance(x, ast.Select):
                    yield x
                elif isinstance(x, tuple):
                    for y in x:
                        if isinstance(y, ast.Node) and not isinstance(
                            y, ast.Select
                        ):
                            yield y


def _find_scalar_subqueries(e: ast.Node) -> List["ast.ScalarSubquery"]:
    """All ScalarSubquery nodes in an expression (not descending into
    them — nesting belongs to the inner query's own planning)."""
    out: List[ast.ScalarSubquery] = []

    def walk(n):
        if isinstance(n, ast.ScalarSubquery):
            out.append(n)
            return
        for c in _ast_children(n):
            walk(c)

    walk(e)
    return out


def _fold_constant(e):
    """Fold integer Literal-Literal arithmetic (the `1999 + 1` of IN
    lists and ROLLUP windows) into one Literal; anything else passes
    through unchanged."""
    if (
        isinstance(e, E.Arithmetic)
        and e.op in ("+", "-", "*")
        and isinstance(e.left, E.Literal)
        and isinstance(e.right, E.Literal)
        and e.left.value is not None
        and e.right.value is not None
        and e.left.dtype.is_integer
        and e.right.dtype.is_integer
    ):
        a, b = int(e.left.value), int(e.right.value)
        v = a + b if e.op == "+" else (a - b if e.op == "-" else a * b)
        return E.Literal(v, e.dtype)
    return e


def _contains_select(e) -> bool:
    """True when a nested Select (sub)query appears anywhere in ``e``."""
    if isinstance(e, ast.Select):
        return True
    if not isinstance(e, ast.Node):
        return False
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, ast.Node):
            if _contains_select(v):
                return True
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, ast.Node) and _contains_select(x):
                    return True
                if isinstance(x, tuple):
                    for y in x:
                        if isinstance(y, ast.Node) and _contains_select(y):
                            return True
    return False


def _contains_membership_subquery(e: ast.Node) -> bool:
    """True when an IN-subquery or EXISTS hides inside ``e`` (not as
    the whole conjunct — those take the semi/anti fast path); such
    conjuncts lower via mark joins."""
    if isinstance(e, (ast.InSubquery, ast.Exists)):
        return True
    if isinstance(e, ast.Select) or not isinstance(e, ast.Node):
        return False
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, ast.Node):
            if _contains_membership_subquery(v):
                return True
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(
                    x, ast.Node
                ) and _contains_membership_subquery(x):
                    return True
    return False


def _split_conjuncts(e: ast.Node) -> List[ast.Node]:
    if isinstance(e, ast.BinaryOp) and e.op == "and":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _split_disjuncts(e: ast.Node) -> List[ast.Node]:
    if isinstance(e, ast.BinaryOp) and e.op == "or":
        return _split_disjuncts(e.left) + _split_disjuncts(e.right)
    return [e]


def _and_join(terms: List[ast.Node]) -> ast.Node:
    out = terms[0]
    for t in terms[1:]:
        out = ast.BinaryOp("and", out, t)
    return out


def _factor_or(c: ast.Node) -> List[ast.Node]:
    """Factor conjuncts common to every OR branch up to the top level —
    `(k=j and A) or (k=j and B)` -> `k=j and (A or B)`. This is how Q19's
    join key, repeated inside each OR arm, becomes visible to the join
    graph (reference: equivalent extraction in PushdownFilters)."""
    if not (isinstance(c, ast.BinaryOp) and c.op == "or"):
        return [c]
    branch_conjs = [_split_conjuncts(b) for b in _split_disjuncts(c)]
    common = [
        x for x in branch_conjs[0] if all(x in bc for bc in branch_conjs[1:])
    ]
    if not common:
        return [c]
    remaining = []
    all_empty = True
    for bc in branch_conjs:
        rest = [x for x in bc if x not in common]
        if rest:
            all_empty = False
            remaining.append(_and_join(rest))
        else:
            remaining.append(ast.BoolLit(True))
    if all_empty:
        return common
    reduced = remaining[0]
    for r in remaining[1:]:
        reduced = ast.BinaryOp("or", reduced, r)
    return common + [reduced]


def _number_literal(text: str) -> E.Literal:
    if "e" in text:
        return E.Literal(float(text), T.DOUBLE)
    if "." in text:
        digits = text.replace(".", "").lstrip("0") or "0"
        scale = len(text.split(".")[1])
        unscaled = int(text.replace(".", ""))
        return E.Literal(unscaled, T.decimal(max(len(digits), scale + 1), scale))
    return E.Literal(int(text), T.BIGINT)


def _coerce_literal(lit: E.Literal, to: T.DataType) -> E.Literal:
    v = lit.value
    if to.is_decimal and lit.dtype.is_integer:
        return E.Literal(int(v) * 10 ** to.scale, to)
    if to.is_decimal and lit.dtype.is_decimal:
        # decimal literals store UNSCALED values: rescale, don't retype
        shift = to.scale - lit.dtype.scale
        if shift >= 0:
            return E.Literal(int(v) * 10 ** shift, to)
        return E.Literal(int(v) // 10 ** (-shift), to)
    if to.is_integer and lit.dtype.is_integer:
        return E.Literal(int(v), to)
    if to.name == "date" and lit.dtype.is_integer:
        return E.Literal(int(v), to)
    return E.Literal(v, to)


def _parse_date(s: str) -> int:
    d = datetime.date.fromisoformat(s.strip())
    return (d - datetime.date(1970, 1, 1)).days


from presto_tpu.plan import optimizer  # noqa: E402


# Deferred join pool (internal to planning; resolved before execution)


@dataclasses.dataclass(frozen=True)
class _PendingJoin(N.PlanNode):
    rels: Tuple[N.PlanNode, ...]
    scopes: Tuple[object, ...]

    def output_schema(self):
        out = {}
        for r in self.rels:
            out.update(r.output_schema())
        return out

    def children(self):
        return self.rels
