"""Logical planner, analyzer, optimizer, fragmenter.

Reference parity: ``presto-main`` ``…/sql/planner/`` — ``LogicalPlanner``
(AST -> PlanNode tree), ``PlanOptimizers`` (rule passes), ``PlanFragmenter``
(SURVEY.md §2.1). The analyzer (name/type resolution) is fused into the
planner here, lowering parse-tree expressions into the typed
presto_tpu.expr IR as scopes are built.

TPU-first: plan nodes carry the *static* shape metadata XLA needs
(capacity buckets, max_groups, join out_capacity) chosen from connector
stats, so a whole plan compiles to one jitted program over staged scan
pages (SURVEY.md §7 "Design stance"); overflow flags trigger host-side
re-planning at bigger buckets.
"""

from presto_tpu.plan.nodes import *  # noqa: F401,F403
from presto_tpu.plan.planner import plan_statement  # noqa: F401
