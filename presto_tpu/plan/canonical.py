"""Literal parameterization + the parameterized plan cache.

Reference parity: Presto's prepared statements and plan hashing (the
L1/L2 serving amortizations in PAPER.md) — the same query *shape*
arriving thousands of times with different literals must not re-pay
parse -> plan -> optimize -> compile per arrival. On this engine the
stake is far higher than the reference's microsecond planner pass: a
cache miss is an XLA compile (seconds).

Two cooperating layers, both owned by THIS module (lint:
tools/check_plan_params.py):

1. **Plan-level hoisting** (:func:`hoist_params`) — just before a plan
   compiles, eligible ``expr.Literal`` leaves are hoisted into
   ``expr.RuntimeParam`` slots and their values become a parameter
   vector that enters the jitted program as *device inputs*. The
   compile cache keys on the canonical (literal-free) fingerprint, so
   ``WHERE l_quantity < 24`` and ``< 30`` share ONE compiled program.
   Runs on every executor tier — local runner, streamed fragments, and
   workers (each worker canonicalizes the fragments it receives, so
   literal-variant fragments hit the worker compile cache too).

2. **Statement-level plan cache** (:class:`PlanCache`) — bare
   NumberLit/DateLit comparison operands in WHERE / HAVING / JOIN-ON
   are rewritten to ``ast.BoundParam`` placeholders; the canonical
   AST's repr (plus catalog/schema) keys a bounded LRU of planned +
   optimized plans. A hit skips parse-tree analysis, planning, and
   optimization entirely and binds the new literal values straight
   into the cached plan's RuntimeParam slots. PREPARE/EXECUTE rides
   this: a warm EXECUTE does zero planning and zero compilation.

Eligibility (the dtype/shape bucketing rules — everything else stays a
trace-time constant, bucketing the cache rather than breaking it):

- strings stay constants: dictionary comparisons resolve literal ids
  against the column's trace-time dictionary host-side;
- NULL literals stay constants: a NULL's validity lane is program
  structure, not a value;
- long decimals (int128 limb pairs) stay constants: their lowering
  takes literal-introspection fast paths;
- booleans stay constants (two buckets at most, often folded);
- a literal multiplying/dividing a long-decimal operand stays constant
  (the limb-multiply fast path requires a compile-time small int);
- structure-controlling integers are not literals at all by the time
  plans exist (LIMIT counts, capacity buckets, IN-list LENGTHS — the
  list length is the tuple arity, which stays in the fingerprint).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import expr as E
from presto_tpu import types as T
from presto_tpu.plan import nodes as N
from presto_tpu.sql import ast


# ---------------------------------------------------------------- trace-time
# parameter vector (installed by the runner's trace function around
# _execute_node; read by ExprLowerer._eval_runtimeparam)

_ACTIVE = threading.local()


@contextlib.contextmanager
def active_params(params):
    """Install the traced parameter vector for the current trace."""
    prev = getattr(_ACTIVE, "value", None)
    _ACTIVE.value = params
    try:
        yield
    finally:
        _ACTIVE.value = prev


def active_param(index: int):
    params = getattr(_ACTIVE, "value", None)
    if params is None or index >= len(params):
        raise RuntimeError(
            f"RuntimeParam slot {index} evaluated outside an "
            "active parameter vector (plan/canonical.py owns hoisting "
            "and binding — see tools/check_plan_params.py)"
        )
    return params[index]


# ------------------------------------------------------------- eligibility


def _hoistable(lit: E.Literal) -> bool:
    """May this literal become a runtime parameter? (module docstring
    spells out each exclusion)."""
    if lit.value is None:
        return False
    t = lit.dtype
    if t.is_string or t.is_long_decimal:
        return False
    if t.name == "boolean":
        return False
    if not (t.is_numeric or t.name in ("date", "timestamp")):
        return False
    return isinstance(lit.value, (int, float)) and not isinstance(
        lit.value, bool
    )


def _param_np(value, dtype: T.DataType):
    """Host-side image of one parameter: a () ndarray in the literal's
    NATIVE dtype, so the jitted program's parameter avals are stable
    across executions (dtype bucketing — int64 and float32 variants
    are different canonical forms, never a silent cast)."""
    return np.asarray(value, dtype=dtype.np_dtype)


# ------------------------------------------------- plan-level hoisting pass


class _Hoist:
    """One hoisting pass over a plan tree: collects the parameter
    vector while rewriting eligible Literal leaves to RuntimeParam
    slots and re-indexing pre-bound RuntimeParams (statement-cache
    plans) against ``bound``."""

    def __init__(self, bound, hoist_literals: bool):
        self.bound = bound or {}
        self.hoist_literals = hoist_literals
        self.values: List[np.ndarray] = []

    def _bound_lit(self, e: E.RuntimeParam) -> E.Literal:
        lit = self.bound.get(e.index)
        if lit is None:
            raise RuntimeError(
                f"RuntimeParam slot {e.index} has no bound value "
                "(a cached canonical plan executed without its "
                "parameter vector)"
            )
        return lit

    # leaf hooks — _Bind (bind_literal_root) overrides exactly these,
    # so there is ONE deep expression walker to keep in sync with the
    # Expr dataclasses, not two
    def on_runtime_param(self, e: E.RuntimeParam) -> E.Expr:
        idx = len(self.values)
        self.values.append(_param_np(self._bound_lit(e).value, e.dtype))
        return E.RuntimeParam(idx, e.dtype)

    def on_literal(self, e: E.Literal) -> E.Expr:
        if self.hoist_literals and _hoistable(e):
            idx = len(self.values)
            self.values.append(_param_np(e.value, e.dtype))
            return E.RuntimeParam(idx, e.dtype)
        return e

    def expr(self, e: E.Expr) -> E.Expr:
        if isinstance(e, E.RuntimeParam):
            return self.on_runtime_param(e)
        if isinstance(e, E.Literal):
            return self.on_literal(e)
        if isinstance(e, E.Arithmetic) and (
            e.left.dtype.is_long_decimal or e.right.dtype.is_long_decimal
        ):
            # keep the literal operand constant: long-decimal arithmetic
            # takes a compile-time small-int multiply fast path
            changes = {}
            for name in ("left", "right"):
                v = getattr(e, name)
                if not isinstance(v, E.Literal):
                    nv = self.expr(v)
                    if nv is not v:
                        changes[name] = nv
            return dataclasses.replace(e, **changes) if changes else e
        if not dataclasses.is_dataclass(e):
            return e
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, E.Expr):
                nv = self.expr(v)
                if nv is not v:
                    changes[f.name] = nv
            elif isinstance(v, tuple):
                nt = tuple(
                    self.expr(x)
                    if isinstance(x, E.Expr)
                    else (
                        tuple(
                            self.expr(y) if isinstance(y, E.Expr) else y
                            for y in x
                        )
                        if isinstance(x, tuple)
                        else x
                    )
                    for x in v
                )
                if any(a is not b for a, b in zip(nt, v)):
                    changes[f.name] = nt
        return dataclasses.replace(e, **changes) if changes else e

    # expr-bearing plan-node fields the pass rewrites; everything else
    # (scan constraints, sort keys, window calls) stays constant — sort
    # and window literals can control kernel structure, and a scan
    # constraint IS the value (split pruning)
    def node(self, node: N.PlanNode) -> N.PlanNode:
        changes = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, N.PlanNode):
                nv = self.node(v)
                if nv is not v:
                    changes[f.name] = nv
            elif (
                isinstance(v, tuple) and v and isinstance(v[0], N.PlanNode)
            ):
                nt = tuple(self.node(x) for x in v)
                if any(a is not b for a, b in zip(nt, v)):
                    changes[f.name] = nt
        if isinstance(node, N.FilterNode):
            np_ = self.expr(node.predicate)
            if np_ is not node.predicate:
                changes["predicate"] = np_
        elif isinstance(node, N.ProjectNode):
            projs = tuple(
                (name, self.expr(e)) for name, e in node.projections
            )
            if any(
                a[1] is not b[1] for a, b in zip(projs, node.projections)
            ):
                changes["projections"] = projs
        elif isinstance(node, N.JoinNode):
            if node.residual is not None:
                nr = self.expr(node.residual)
                if nr is not node.residual:
                    changes["residual"] = nr
        elif isinstance(node, N.AggregationNode):
            keys = tuple(
                (name, self.expr(e)) for name, e in node.group_keys
            )
            if any(
                a[1] is not b[1] for a, b in zip(keys, node.group_keys)
            ):
                changes["group_keys"] = keys
            aggs = []
            agg_changed = False
            for a in node.aggs:
                na = a
                if a.arg is not None:
                    arg = self.expr(a.arg)
                    if arg is not a.arg:
                        na = dataclasses.replace(na, arg=arg)
                arg2 = getattr(a, "arg2", None)
                if arg2 is not None:
                    n2 = self.expr(arg2)
                    if n2 is not arg2:
                        na = dataclasses.replace(na, arg2=n2)
                agg_changed |= na is not a
                aggs.append(na)
            if agg_changed:
                changes["aggs"] = tuple(aggs)
        elif isinstance(node, N.UnnestNode):
            if node.elements:
                els = tuple(self.expr(e) for e in node.elements)
                if any(a is not b for a, b in zip(els, node.elements)):
                    changes["elements"] = els
        return (
            dataclasses.replace(node, **changes) if changes else node
        )


def hoist_params(
    root: N.PlanNode,
    bound: Optional[Dict[int, E.Literal]] = None,
    hoist_literals: bool = True,
) -> Tuple[N.PlanNode, Tuple[np.ndarray, ...]]:
    """Canonicalize ``root`` for compilation: eligible literals hoist
    into RuntimeParam slots (when ``hoist_literals``), statement-cache
    RuntimeParams re-index densely against ``bound``, and the matching
    parameter vector (host () ndarrays in native dtypes) is returned.
    Identity-preserving: an unchanged tree returns ``root`` itself with
    an empty vector — the exact pre-cache compile path."""
    h = _Hoist(bound, hoist_literals)
    croot = h.node(root)
    return croot, tuple(h.values)


class _Bind(_Hoist):
    """RuntimeParam -> plain Literal substitution over the SAME walker
    as hoisting (only the leaf hooks differ)."""

    def on_runtime_param(self, e: E.RuntimeParam) -> E.Expr:
        return E.Literal(self._bound_lit(e).value, e.dtype)

    def on_literal(self, e: E.Literal) -> E.Expr:
        return e


class _Normalize(_Hoist):
    """Literal-invariant normalization over the SAME walker as hoisting:
    every hoistable literal AND every RuntimeParam slot collapses to the
    ONE placeholder ``RuntimeParam(0, dtype)``, so a literal-form tree
    and its hoisted canonical form normalize identically. This is the
    value-erasing image history fingerprints digest (plan/history.py) —
    NOT an executable tree (slot 0 is deliberately shared)."""

    def __init__(self):
        super().__init__({}, True)

    def on_runtime_param(self, e: E.RuntimeParam) -> E.Expr:
        return E.RuntimeParam(0, e.dtype)

    def on_literal(self, e: E.Literal) -> E.Expr:
        if _hoistable(e):
            return E.RuntimeParam(0, e.dtype)
        return e


def normalize_expr(e: E.Expr) -> E.Expr:
    """Value-erased image of one expression for history fingerprinting
    (plan/history.py is the only intended consumer): hoistable literals
    and RuntimeParams become index-0 placeholders; everything the
    hoisting pass would keep constant (strings, NULLs, long decimals,
    booleans, long-decimal-arithmetic operands) stays in place — the
    SAME eligibility rules, via the same walker."""
    return _Normalize().expr(e)


def bind_literal_root(
    root: N.PlanNode, bound: Optional[Dict[int, E.Literal]]
) -> N.PlanNode:
    """Substitute bound values back as plain Literals (the no-hoist
    fallback and the distributed materialize path: a literal-form tree
    with no RuntimeParam leaves)."""
    return _Bind(bound, False).node(root)


def materialize_plan(plan):
    """A literal (RuntimeParam-free) copy of a cached plan — the
    distributed path ships fragments with plain literals so the wire
    protocol and worker-side execution are unchanged; workers then
    re-hoist locally and hit their own compile caches across literal
    variants."""
    from presto_tpu.plan.planner import Plan

    if not plan.bound_values:
        return plan
    root = bind_literal_root(plan.root, plan.bound_values)
    # scalar-subquery subplans share the statement's ordinal space:
    # materialize them against the same bound map
    params = [
        (pid, materialize_plan(_with_bound(sub, plan.bound_values)))
        for pid, sub in plan.params
    ]
    return Plan(
        root=root,
        params=params,
        output_names=plan.output_names,
        bound_values=None,
        preoptimized=plan.preoptimized,
    )


def _with_bound(plan, bound):
    from presto_tpu.plan.planner import Plan

    return Plan(
        root=plan.root,
        params=plan.params,
        output_names=plan.output_names,
        bound_values=bound,
        preoptimized=getattr(plan, "preoptimized", False),
    )


# ------------------------------------------------- micro-batch compile plane
#
# Many concurrent statements of ONE canonical fingerprint differ only in
# their RuntimeParam vectors (that is the whole point of hoisting). The
# micro-batch serving plane answers N of them with ONE device dispatch:
# the members' parameter vectors stack along a new leading batch axis
# and the existing scalar trace runs under ``jax.vmap`` with the staged
# pages broadcast. Everything batch-axis-shaped is constructed HERE —
# like the other compile-plane invariants (tools/analyze.py
# ``serving-batch`` rule): a stacking or vmap entry built elsewhere
# could silently disagree with the eligibility/dtype rules above and
# cross members' answers.

#: lane-count buckets for batched compile entries: a warm batch of any
#: size up to the bucket reuses the bucket's ONE compiled program
#: (padded lanes repeat a member's params; their outputs are dropped at
#: demux) — without bucketing every distinct group size would pay its
#: own XLA compile
_BATCH_LANE_BUCKETS = (2, 4, 8, 16, 32, 64, 128)


def batch_lanes(n: int) -> int:
    """Smallest lane bucket holding ``n`` members (n > the largest
    bucket is the caller's error: serving.microbatch-max caps groups)."""
    for b in _BATCH_LANE_BUCKETS:
        if n <= b:
            return b
    raise ValueError(
        f"micro-batch of {n} exceeds the largest lane bucket "
        f"{_BATCH_LANE_BUCKETS[-1]}"
    )


def stack_param_vectors(
    vectors: List[Tuple[np.ndarray, ...]], lanes: int
) -> Tuple[np.ndarray, ...]:
    """Stack N members' parameter vectors along a NEW leading batch
    axis, padded to ``lanes`` by repeating the last member (padding
    lanes compute a real member's answer; demux drops them). Every
    member must carry the same arity and per-slot dtype — guaranteed
    when the members share one canonical fingerprint (dtype bucketing
    is part of the canonical form), re-checked here because a mismatch
    would cross members' answers, not just miss a cache."""
    if not vectors or lanes < len(vectors):
        raise ValueError("stack_param_vectors: bad lane count")
    arity = len(vectors[0])
    for v in vectors:
        if len(v) != arity:
            raise ValueError(
                "micro-batch members disagree on parameter arity"
            )
        for a, b in zip(v, vectors[0]):
            if a.dtype != b.dtype or a.shape != b.shape:
                raise ValueError(
                    "micro-batch members disagree on parameter dtype"
                )
    padded = list(vectors) + [vectors[-1]] * (lanes - len(vectors))
    return tuple(
        np.stack([v[i] for v in padded]) for i in range(arity)
    )


def vmap_program(trace_fn):
    """The ONE batched-entry constructor: vmap the scalar trace over
    the parameter axis with the staged pages broadcast. The jitted
    result is cached beside the scalar entry under
    :func:`batch_entry_key` — a cold batch costs one compile, warm
    batches zero."""
    import jax

    return jax.vmap(trace_fn, in_axes=(None, 0))


def batch_entry_key(
    cfp: str, counted: bool, offload: bool, lanes: int, window: int
) -> tuple:
    """Compile-cache key of a batched entry: the scalar canonical
    fingerprint plus the lane bucket and the demux window (the batched
    program compacts each lane to the window, so the window is shape),
    tagged so it can never collide with (or be served as) a scalar
    entry."""
    return (cfp, False, counted, offload, "batch", lanes, window)


# ---------------------------------------------- statement canonicalization

#: comparison operators whose bare literal operands are safe to hoist at
#: the AST level: the analyzer lowers them through the one generic
#: comparison path (planner._lower BinaryOp/Between/InList)
_CMP_OPS = ("=", "<>", "!=", "<", "<=", ">", ">=")


def literal_info(node: ast.Node) -> Optional[E.Literal]:
    """ast literal -> typed E.Literal, via the SAME conversions the
    analyzer applies (planner._number_literal / _parse_date) — the
    bound values must be byte-identical to what planning the literal
    in place would have produced."""
    from presto_tpu.plan.planner import _number_literal, _parse_date

    if isinstance(node, ast.NumberLit):
        return _number_literal(node.text)
    if isinstance(node, ast.DateLit):
        return E.Literal(_parse_date(node.value), T.DATE)
    return None


class _AstCanon:
    """Rewrites bare NumberLit/DateLit comparison operands in
    WHERE / HAVING / JOIN-ON predicates (including those inside
    subqueries, CTEs and set-operation terms) to BoundParam
    placeholders, collecting their typed values by ordinal."""

    def __init__(self):
        self.values: List[E.Literal] = []

    def maybe_param(self, node: ast.Node) -> ast.Node:
        lit = literal_info(node)
        if lit is None or not _hoistable(lit):
            return node
        ordinal = len(self.values)
        self.values.append(lit)
        return ast.BoundParam(
            ordinal=ordinal, dtype_name=str(lit.dtype), lit=node
        )

    def pred(self, e: ast.Node) -> ast.Node:
        if isinstance(e, ast.BinaryOp):
            if e.op in ("and", "or"):
                return dataclasses.replace(
                    e, left=self.pred(e.left), right=self.pred(e.right)
                )
            if e.op in _CMP_OPS:
                return dataclasses.replace(
                    e,
                    left=self.maybe_param(e.left),
                    right=self.maybe_param(e.right),
                )
            return e
        if isinstance(e, ast.UnaryOp) and e.op == "not":
            return dataclasses.replace(e, arg=self.pred(e.arg))
        if isinstance(e, ast.BetweenExpr):
            return dataclasses.replace(
                e,
                low=self.maybe_param(e.low),
                high=self.maybe_param(e.high),
            )
        if isinstance(e, ast.InList):
            return dataclasses.replace(
                e, values=tuple(self.maybe_param(v) for v in e.values)
            )
        if isinstance(e, ast.InSubquery):
            return dataclasses.replace(e, query=self.select(e.query))
        if isinstance(e, ast.Exists):
            return dataclasses.replace(e, query=self.select(e.query))
        if isinstance(e, ast.ScalarSubquery):
            return dataclasses.replace(e, query=self.select(e.query))
        return e

    def rel(self, r):
        if r is None:
            return r
        if isinstance(r, ast.SubqueryRef):
            return dataclasses.replace(r, query=self.select(r.query))
        if isinstance(r, ast.JoinRel):
            return dataclasses.replace(
                r,
                left=self.rel(r.left),
                right=self.rel(r.right),
                on=self.pred(r.on) if r.on is not None else None,
            )
        if isinstance(r, ast.UnionRel):
            return dataclasses.replace(
                r, terms=tuple(self.select(t) for t in r.terms)
            )
        return r

    def select(self, sel: ast.Select) -> ast.Select:
        return dataclasses.replace(
            sel,
            from_=self.rel(sel.from_),
            where=(
                self.pred(sel.where) if sel.where is not None else None
            ),
            having=(
                self.pred(sel.having)
                if sel.having is not None
                else None
            ),
            ctes=tuple(
                (name, self.select(q)) for name, q in sel.ctes
            ),
        )


def canonicalize_statement(
    stmt: ast.Select, session
) -> Tuple[str, ast.Select, List[E.Literal]]:
    """-> (cache key, canonical statement, hoisted values by ordinal).
    The key is the canonical AST's repr — BoundParam prints its ordinal
    and dtype but never its value — prefixed with the session's
    catalog/schema (name resolution depends on them). Non-hoisted
    literals keep their values in the repr, so variance there simply
    keys separate entries (correct, just less sharing)."""
    c = _AstCanon()
    canon = c.select(stmt)
    key = f"{session.catalog}|{session.schema}|{canon!r}"
    return key, canon, c.values


# ----------------------------------------------------------- the plan cache


@dataclasses.dataclass
class PlanCacheEntry:
    root: N.PlanNode
    params: list
    output_names: tuple
    preoptimized: bool
    handles: frozenset
    n_slots: int
    #: adaptive execution (epoch-versioned entries): the history
    #: evidence this plan's optimization consulted — node fingerprint
    #: -> {"epoch", "rows", "est"} captured by
    #: plan/history.capture_consults around planning. A later hit
    #: re-validates against it (:func:`stale_consults`); empty = the
    #: entry can never go stale (no history was consulted).
    consulted: dict = dataclasses.field(default_factory=dict)


def stale_consults(consulted: dict, store, factor: float):
    """The statement-cache REPLAN seam's divergence test (adaptive
    execution — the one place a cached plan is judged stale; audited
    consumer: exec/local_runner._adaptive_replan).

    -> ``(fp, captured_epoch, current_epoch)`` of the first consulted
    node whose learned cardinality has since MATERIALLY diverged from
    the estimate this plan was built on, else None. The epoch
    comparison is the cheap pre-filter (epochs bump only on material
    change — plan/history.record_query) — valid only when the
    caller's factor is at least the STORE's bump factor; a tighter
    session factor falls through to the full per-node judgement, so
    ``adaptive_divergence_factor=2`` still replans on drift the
    store's 4x epochs never flagged. The captured estimate (the
    learned rows at consult time, or the classic fallback on a miss)
    is what the fresh learned value is judged against, so an epoch
    bump that lands back NEAR the plan's own assumptions keeps the
    plan. Never raises — staleness checking must not fail a query."""
    from presto_tpu.plan import history

    epoch_gated = factor >= getattr(store, "divergence_factor", 1.0)
    for fp, cap in (consulted or {}).items():
        try:
            cur_epoch = store.epoch_of(fp)
            if epoch_gated and cur_epoch == cap.get("epoch", 0):
                continue
            learned = store.learned_rows(fp)
            base = cap.get("rows")
            if base is None:
                base = cap.get("est")
            if learned is None or base is None:
                continue
            if history.diverged(base, learned, factor):
                return fp, cap.get("epoch", 0), cur_epoch
        except Exception:
            continue
    return None


#: sentinel: this canonical shape could not be planned in parameterized
#: form (a hoisted literal sat in a structural position) — plan it with
#: literals in place, forever, without re-paying the failed attempt
BYPASS = object()


class PlanCache:
    """Bounded LRU of parameterized plans keyed on canonical statement
    form (tier-1 ``plan.cache-entries``), with write-path invalidation
    by table handle — riding the same hooks as the split cache, because
    a DROP/recreate can change the schema a cached plan was resolved
    against."""

    def __init__(self, entries: int = 256):
        self._entries = max(int(entries), 0)
        self._lock = threading.Lock()
        self._od: "OrderedDict[str, object]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        #: adaptive execution: hits whose entry was judged stale
        #: (stale_consults) and replaced by a fresh plan
        self.replans = 0

    def resize(self, entries: int) -> None:
        with self._lock:
            self._entries = max(int(entries), 0)
            self._shrink()

    def _shrink(self) -> None:
        from presto_tpu.utils.metrics import REGISTRY

        while len(self._od) > self._entries:
            self._od.popitem(last=False)
            self.evictions += 1
            REGISTRY.counter("plan.cache_evict").update()

    def get(self, key: str):
        """-> PlanCacheEntry | BYPASS | None, counting hit/miss (a
        BYPASS lookup counts as a miss: the caller plans fresh)."""
        from presto_tpu.utils.metrics import REGISTRY

        with self._lock:
            e = self._od.get(key)
            if isinstance(e, PlanCacheEntry):
                self._od.move_to_end(key)
                self.hits += 1
                REGISTRY.counter("plan.cache_hit").update()
                return e
            self.misses += 1
            REGISTRY.counter("plan.cache_miss").update()
            return e

    def put(self, key: str, entry) -> None:
        with self._lock:
            self._od[key] = entry
            self._od.move_to_end(key)
            self._shrink()

    def note_replan(self) -> None:
        """Count one adaptive replan under the cache lock (like every
        other counter here — concurrent stale hits must not lose
        updates against the stats row)."""
        with self._lock:
            self.replans += 1

    def invalidate(self, handle) -> None:
        # version-blind match: a cached plan pins a SNAPSHOT of its
        # tables (planner pin_snapshot), and a write/commit must drop
        # plans planned against any version of the written table
        tk = handle.table_key
        with self._lock:
            dead = [
                k
                for k, e in self._od.items()
                if isinstance(e, PlanCacheEntry)
                and any(h.table_key == tk for h in e.handles)
            ]
            for k in dead:
                del self._od[k]

    def clear(self) -> None:
        with self._lock:
            self._od.clear()

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": sum(
                    1
                    for e in self._od.values()
                    if isinstance(e, PlanCacheEntry)
                ),
                "capacity": self._entries,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "replans": self.replans,
            }


def plan_handles(plan) -> frozenset:
    """Every TableHandle a plan (incl. scalar-subquery subplans)
    scans — the invalidation index of its cache entry."""
    out = set()

    def add_root(root):
        for n in N.walk(root):
            if isinstance(n, N.TableScanNode):
                out.add(n.handle)

    add_root(plan.root)
    for _pid, sub in plan.params:
        for h in plan_handles(sub):
            out.add(h)
    return frozenset(out)
