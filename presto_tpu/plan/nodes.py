"""Logical plan node hierarchy.

Reference parity: presto-main's ``PlanNode`` tree — TableScanNode,
FilterNode, ProjectNode, AggregationNode, JoinNode, SortNode (TopN fused
via limit), LimitNode, WindowNode, OutputNode, ValuesNode (SURVEY.md
§2.1 "Logical planner"). SemiJoin/anti are JoinNode join_types, as in the
executor kernels.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu import types as T
from presto_tpu.connectors.spi import TableHandle
from presto_tpu.expr import Expr
from presto_tpu.ops.aggregation import AggCall
from presto_tpu.ops.sort import SortKey
from presto_tpu.ops.window import WindowCall


class PlanNode:
    def output_schema(self) -> Dict[str, T.DataType]:
        raise NotImplementedError

    def children(self) -> Sequence["PlanNode"]:
        return ()

    def fingerprint(self) -> str:
        """Stable id for the jit plan cache."""
        return repr(self)


@dataclasses.dataclass(frozen=True)
class TableScanNode(PlanNode):
    handle: TableHandle
    columns: Tuple[str, ...]
    schema: Tuple[Tuple[str, T.DataType], ...]  # ordered (name, type)
    #: TupleDomain-lite pushdown (reference: TupleDomain reaching
    #: ConnectorSplitManager): (column, allowed literal values) pairs
    #: derived from filters ABOVE the scan — advisory for split
    #: enumeration (hive partition pruning); the filter itself still
    #: applies, so ignoring the constraint is always correct.
    constraint: Tuple[Tuple[str, Tuple], ...] = ()

    def output_schema(self):
        return dict(self.schema)


@dataclasses.dataclass(frozen=True)
class ValuesNode(PlanNode):
    """Single-row relation for FROM-less SELECT (reference: ValuesNode)."""

    schema: Tuple[Tuple[str, T.DataType], ...] = ()

    def output_schema(self):
        return dict(self.schema)


@dataclasses.dataclass(frozen=True)
class FilterNode(PlanNode):
    source: PlanNode
    predicate: Expr
    #: runtime dynamic filter (build->probe, exec/dynfilter.py): the
    #: executor traces this node's pruned-row count as a program
    #: output (dynamic_filter.rows_pruned observability)
    dynamic: bool = False

    def output_schema(self):
        return self.source.output_schema()

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class ProjectNode(PlanNode):
    source: PlanNode
    projections: Tuple[Tuple[str, Expr], ...]

    def output_schema(self):
        return {n: e.dtype for n, e in self.projections}

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class AggregationNode(PlanNode):
    source: PlanNode
    group_keys: Tuple[Tuple[str, Expr], ...]
    aggs: Tuple[AggCall, ...]
    max_groups: int = 1 << 16  # capacity bucket; optimizer refines by stats

    def output_schema(self):
        out = {n: e.dtype for n, e in self.group_keys}
        for a in self.aggs:
            out[a.out_name] = a.result_type()
        return out

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class JoinNode(PlanNode):
    left: PlanNode  # probe
    right: PlanNode  # build
    join_type: str  # inner | left | full | semi | anti
    left_keys: Tuple[str, ...]
    right_keys: Tuple[str, ...]
    payload: Tuple[str, ...]  # build columns carried to output
    payload_rename: Tuple[Tuple[str, str], ...] = ()
    build_unique: bool = False
    out_capacity: Optional[int] = None  # None: planner fills from stats
    residual: Optional[Expr] = None  # non-equi conjuncts applied post-join

    def output_schema(self):
        out = dict(self.left.output_schema())
        rename = dict(self.payload_rename)
        if self.join_type in ("inner", "left", "full"):
            rs = self.right.output_schema()
            for c in self.payload:
                out[rename.get(c, c)] = rs[c]
        return out

    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass(frozen=True)
class CrossJoinNode(PlanNode):
    """Cross product. ``out_capacity=None``: single-row right side only
    (scalar-aggregate broadcast — the common SQL shape, no expansion).
    With ``out_capacity``: general nested-loop product (reference:
    NestedLoopJoinOperator) under the capacity-bucket overflow
    protocol."""

    left: PlanNode
    right: PlanNode
    out_capacity: Optional[int] = None

    def output_schema(self):
        return {**self.left.output_schema(), **self.right.output_schema()}

    def children(self):
        return (self.left, self.right)


@dataclasses.dataclass(frozen=True)
class SortNode(PlanNode):
    source: PlanNode
    keys: Tuple[SortKey, ...]
    limit: Optional[int] = None  # fused TopN

    def output_schema(self):
        return self.source.output_schema()

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class LimitNode(PlanNode):
    source: PlanNode
    count: int

    def output_schema(self):
        return self.source.output_schema()

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class DistinctNode(PlanNode):
    source: PlanNode
    max_groups: int = 1 << 16

    def output_schema(self):
        return self.source.output_schema()

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class WindowNode(PlanNode):
    source: PlanNode
    partition_by: Tuple[Expr, ...]
    order_by: Tuple[SortKey, ...]
    calls: Tuple[WindowCall, ...]

    def output_schema(self):
        out = dict(self.source.output_schema())
        for c in self.calls:
            out[c.out_name] = c.result_type()
        return out

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class UnnestNode(PlanNode):
    """CROSS JOIN UNNEST(...) AS a(col [, ord]) — reference: UnnestNode
    (presto-main logical plan). Two forms:

    - constructor form (``elements``): ARRAY[e1..ek] is a trace-time
      expression list, so unnest is a static-width row expansion —
      every input row yields exactly k output rows (capacity x k,
      shapes static for XLA);
    - column form (``array_column``): a physical array column expands
      by per-row lengths under the engine's capacity-bucket protocol
      (``out_capacity`` + overflow retry)."""

    source: PlanNode
    elements: Tuple[Expr, ...]  # all pre-coerced to out_type
    out_name: str
    out_type: T.DataType
    ordinality_name: Optional[str] = None
    array_column: Optional[str] = None  # column form
    out_capacity: Optional[int] = None  # column form output bucket

    def output_schema(self):
        out = dict(self.source.output_schema())
        if self.array_column is not None:
            # column form drops nested columns (their repeated rows
            # could exceed the flat value capacity; see ops.unnest_column)
            out = {n: t for n, t in out.items() if not t.is_nested}
        out[self.out_name] = self.out_type
        if self.ordinality_name is not None:
            out[self.ordinality_name] = T.BIGINT
        return out

    def children(self):
        return (self.source,)


@dataclasses.dataclass(frozen=True)
class UnionAllNode(PlanNode):
    """UNION ALL: page concatenation (reference: UnionNode/ExchangeNode
    with multiple sources). The planner aligns every source to the same
    column names/types via projections; UNION DISTINCT is this node
    under a DistinctNode. TPU-first: concatenation of static-shape
    pages (capacities add), with string columns re-encoded through a
    trace-time union dictionary."""

    sources: Tuple[PlanNode, ...]

    def output_schema(self):
        return self.sources[0].output_schema()

    def children(self):
        return self.sources


@dataclasses.dataclass(frozen=True)
class RemoteSourceNode(PlanNode):
    """Fragment boundary: reads the gathered output of a distributed
    fragment (reference: RemoteSourceNode reading an upstream stage
    through the exchange, SURVEY.md §3.4). ``children()`` is empty on
    purpose — the fragment executes separately; walking the consuming
    fragment must not descend into it."""

    fragment_root: PlanNode

    def output_schema(self):
        return self.fragment_root.output_schema()


@dataclasses.dataclass(frozen=True)
class OutputNode(PlanNode):
    """Final column selection + user-visible names (reference: OutputNode)."""

    source: PlanNode
    columns: Tuple[Tuple[str, str], ...]  # (output name, source column)

    def output_schema(self):
        src = self.source.output_schema()
        return {out: src[col] for out, col in self.columns}

    def children(self):
        return (self.source,)


def walk(node: PlanNode):
    yield node
    for c in node.children():
        yield from walk(c)


def map_children(node: PlanNode, fn) -> PlanNode:
    """Rebuild ``node`` with ``fn`` applied to every direct child plan
    node — including tuple-of-PlanNode fields (UnionAllNode.sources) —
    returning ``node`` unchanged when nothing changed. The one
    child-rewrite loop every generic plan traversal should use."""
    changes = {}
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, PlanNode):
            nv = fn(v)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple) and v and isinstance(v[0], PlanNode):
            nt = tuple(fn(x) for x in v)
            if any(a is not b for a, b in zip(nt, v)):
                changes[f.name] = nt
    return dataclasses.replace(node, **changes) if changes else node
