"""History-based statistics: canonical node fingerprints + the
persistent query-history store.

Reference parity: Presto's history-based optimization (HBO — PAPER.md
L2): the optimizer plans from *learned* cardinalities recorded by prior
executions of the same plan shape, falling back to connector stats and
heuristics when no history exists. The runtime already measures the
truth (per-operator row counters traced out of every compiled program —
exec/stats.OperatorStats); this module gives those actuals a durable,
literal-invariant identity and feeds them back into
``plan/optimizer.estimate_rows``.

Three pieces, all owned by THIS module (lint:
tools/check_history_sites.py):

1. **Canonical sub-fingerprints** (:func:`node_fingerprint` /
   :func:`node_fingerprints`): a stable digest per plan subtree,
   invariant to literal values (hoistable literals and RuntimeParam
   slots normalize to one placeholder via ``plan/canonical.py``), to
   column pruning (scan column lists, projection lists and join
   payloads are excluded — they never change row counts), and to
   capacity buckets (``max_groups`` / ``out_capacity`` scale on
   overflow retries and must not fork the key). ``WHERE x < 24`` and
   ``< 30`` therefore record under ONE key, and a fragment shipped to
   a worker fingerprints identically to the same subtree inside the
   coordinator's full plan.

2. **QueryHistoryStore**: a bounded, crash-safe on-disk store — JSONL
   segment files under a directory (``history.path``) with an
   in-memory index bounded by ``history.max-entries``. Appends are
   single lines (a torn tail line is skipped at load: corrupt-line
   tolerance); segments rotate and the oldest are deleted once the
   on-disk entry count exceeds the bound. Registered as a
   query-completed listener, so the write path is the SAME path as the
   event sink (exec/stats.QueryHistory.finish). Metrics:
   ``history.{hit,miss,write,evict}``.

3. **The read path** (:func:`using` / :func:`lookup_rows`):
   ``optimizer.estimate_rows`` consults :func:`lookup_rows` before
   connector stats. The store is installed thread-locally around
   planning by the runner (gated on session ``enable_history_stats``;
   ``false`` — or no configured store — leaves every estimate
   bit-exact pre-PR).

4. **The adaptive-execution epoch plane** (ROADMAP item 2 — Presto's
   HBO + adaptive-execution direction): every node fingerprint
   carries a monotonic in-memory **epoch**
   (:meth:`QueryHistoryStore.epoch_of`), bumped when
   :meth:`record_query` *materially* changes the learned cardinality —
   relative change beyond the store's divergence factor
   (``adaptive.divergence-factor``), judged by :func:`diverged`, the
   ONE divergence test both adaptive layers share (the statement-cache
   replan seam in plan/canonical.py and the runtime join-strategy
   switch in the coordinator). Small drift does NOT bump the epoch, so
   cached plans survive noise. :func:`capture_consults` records which
   fingerprints (and which estimates) a planning pass consulted — the
   evidence a plan-cache entry is later re-validated against — and
   :func:`with_overrides` installs mid-query OBSERVED cardinalities so
   the coordinator can re-rank not-yet-scheduled joins by runtime
   truth. Epochs PERSIST through the store: every record (and every
   checkpoint copy at rotation) carries the current epoch beside each
   node's learned rows, and load restores the highest epoch seen — a
   restarted or failed-over coordinator keeps its epoch plane instead
   of silently serving cold-epoch cache hits against warm plans.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from presto_tpu.plan import nodes as N

#: records per on-disk segment file before rotation
_SEGMENT_ENTRIES_MIN = 8

#: relative change beyond which a learned cardinality is considered to
#: CONTRADICT an estimate (tier-1 ``adaptive.divergence-factor`` /
#: session ``adaptive_divergence_factor``)
DEFAULT_DIVERGENCE_FACTOR = 4.0


def diverged(estimate, observed, factor: float) -> bool:
    """The ONE divergence test both adaptive layers share: does the
    observed cardinality contradict the estimate beyond ``factor``
    (symmetric ratio — a 4x factor flags both 4x-over and 4x-under)?
    None/negative inputs never diverge — missing evidence must keep
    plans, not invalidate them."""
    if estimate is None or observed is None:
        return False
    try:
        e = float(estimate)
        o = float(observed)
    except (TypeError, ValueError):
        return False
    if e < 0 or o < 0:
        # negative = an unknown-sentinel (FilterSummary.rows uses -1),
        # never real evidence — checked BEFORE the floor clamp, which
        # would otherwise read -1 as "1 row" and diverge spuriously
        return False
    e, o = max(e, 1.0), max(o, 1.0)
    f = max(float(factor), 1.0)
    return o > e * f or e > o * f


# ------------------------------------------------- canonical fingerprints


def _norm_expr(e) -> str:
    """Literal-invariant image of a predicate/expression (hoistable
    literals and RuntimeParam slots collapse to one placeholder —
    plan/canonical.py owns the eligibility rules)."""
    from presto_tpu.plan import canonical

    try:
        return repr(canonical.normalize_expr(e))
    except Exception:
        return repr(e)


def _signature(node: N.PlanNode, memo: Dict[int, str]) -> str:
    """Structural signature of a plan subtree. Deliberately EXCLUDES
    everything optimization rewrites without changing row counts:
    scan column lists / schemas (pruning), scan constraints (advisory
    split pruning; the filter above stays in place), projection lists,
    join payloads/capacities/build_unique, and agg/unnest capacity
    buckets (overflow retries scale them). What remains is exactly the
    cardinality-determining shape."""
    got = memo.get(id(node))
    if got is not None:
        return got
    if isinstance(node, N.TableScanNode):
        h = node.handle
        sig = f"scan[{h.catalog}.{h.schema}.{h.table}]"
    elif isinstance(node, N.FilterNode):
        tag = "dynfilter" if node.dynamic else "filter"
        sig = (
            f"{tag}[{_norm_expr(node.predicate)}]"
            f"({_signature(node.source, memo)})"
        )
    elif isinstance(node, N.ProjectNode):
        # cardinality-preserving: the projection list never changes
        # row counts, and pruning rewrites it freely
        sig = f"project({_signature(node.source, memo)})"
    elif isinstance(node, N.OutputNode):
        sig = f"output({_signature(node.source, memo)})"
    elif isinstance(node, N.JoinNode):
        resid = (
            _norm_expr(node.residual)
            if node.residual is not None
            else ""
        )
        sig = (
            f"join[{node.join_type}|{list(node.left_keys)}="
            f"{list(node.right_keys)}|resid={resid}]"
            f"({_signature(node.left, memo)},"
            f"{_signature(node.right, memo)})"
        )
    elif isinstance(node, N.AggregationNode):
        keys = [_norm_expr(e) for _, e in node.group_keys]
        funcs = [a.func for a in node.aggs]
        sig = (
            f"agg[keys={keys}|funcs={funcs}]"
            f"({_signature(node.source, memo)})"
        )
    elif isinstance(node, N.DistinctNode):
        sig = f"distinct({_signature(node.source, memo)})"
    elif isinstance(node, N.SortNode):
        sig = (
            f"sort[limit={node.limit}]"
            f"({_signature(node.source, memo)})"
        )
    elif isinstance(node, N.LimitNode):
        sig = (
            f"limit[{node.count}]({_signature(node.source, memo)})"
        )
    elif isinstance(node, N.WindowNode):
        sig = f"window({_signature(node.source, memo)})"
    elif isinstance(node, N.UnnestNode):
        n_el = len(node.elements) if node.elements else 0
        arr = node.array_column or ""
        sig = (
            f"unnest[{arr}|{n_el}]({_signature(node.source, memo)})"
        )
    elif isinstance(node, N.UnionAllNode):
        sig = "union({})".format(
            ",".join(_signature(s, memo) for s in node.sources)
        )
    elif isinstance(node, N.RemoteSourceNode):
        sig = f"remote({_signature(node.fragment_root, memo)})"
    elif isinstance(node, N.ValuesNode):
        sig = "values"
    else:
        sig = "{}({})".format(
            type(node).__name__,
            ",".join(_signature(c, memo) for c in node.children()),
        )
    memo[id(node)] = sig
    return sig


def _digest(sig: str) -> str:
    return hashlib.sha1(sig.encode()).hexdigest()[:16]


def node_fingerprint(node: N.PlanNode) -> str:
    """Canonical sub-fingerprint of one plan subtree (literal- and
    optimization-invariant; see :func:`_signature`)."""
    return _digest(_signature(node, {}))


def node_fingerprints(root: N.PlanNode) -> Dict[int, str]:
    """id(node) -> canonical sub-fingerprint for every node of
    ``root``, in one shared-memo pass (the per-compile batch form)."""
    memo: Dict[int, str] = {}
    out: Dict[int, str] = {}
    for n in N.walk(root):
        out[id(n)] = _digest(_signature(n, memo))
    return out


def plan_fingerprint(root: N.PlanNode) -> str:
    """Canonical statement-level fingerprint: the root's subtree
    fingerprint (keys history records and the event-sink enrichment)."""
    return node_fingerprint(root)


# --------------------------------------------------- the read-path scope

_SCOPE = threading.local()


@contextlib.contextmanager
def using(store: Optional["QueryHistoryStore"]):
    """Install ``store`` as the active history provider for the current
    thread (the runner wraps planning in this, gated on session
    ``enable_history_stats``). ``None`` is a no-op scope."""
    prev = getattr(_SCOPE, "store", None)
    prev_memo = getattr(_SCOPE, "memo", None)
    prev_sigs = getattr(_SCOPE, "sigs", None)
    _SCOPE.store = store
    _SCOPE.memo = {}
    _SCOPE.sigs = {}
    try:
        yield
    finally:
        _SCOPE.store = prev
        _SCOPE.memo = prev_memo
        _SCOPE.sigs = prev_sigs


def active_store() -> Optional["QueryHistoryStore"]:
    return getattr(_SCOPE, "store", None)


@contextlib.contextmanager
def capture_consults():
    """Record every fingerprint :func:`lookup_rows` is asked about
    inside this scope, mapping it to the evidence the plan was built
    on: ``{"epoch": store epoch at consult time, "rows": learned
    cardinality or None, "est": the classic estimate used on a miss}``.
    The runner wraps canonical-statement planning in this and stores
    the captured dict on the plan-cache entry — the replan seam
    (plan/canonical.stale_consults) later re-validates the entry
    against it. Nests inside :func:`using`."""
    prev = getattr(_SCOPE, "consulted", None)
    con: Dict[str, dict] = {}
    _SCOPE.consulted = con
    try:
        yield con
    finally:
        _SCOPE.consulted = prev


@contextlib.contextmanager
def with_overrides(rows_by_fp: Optional[Dict[str, float]]):
    """Install mid-query OBSERVED cardinalities (node fingerprint ->
    rows) as the highest-priority estimate source for the current
    thread — the coordinator's runtime adaptation re-ranks the
    not-yet-scheduled join remainder under this after each executed
    stage reports its true output rows. Works with or without a
    backing store (overrides are consulted before it)."""
    prev = getattr(_SCOPE, "overrides", None)
    prev_memo = getattr(_SCOPE, "memo", None)
    prev_sigs = getattr(_SCOPE, "sigs", None)
    _SCOPE.overrides = dict(rows_by_fp or {})
    # fingerprint computation inside lookup_rows rides the scope memo;
    # give overrides-only scopes (no store installed) one too
    if prev_memo is None:
        _SCOPE.memo = {}
        _SCOPE.sigs = {}
    try:
        yield
    finally:
        _SCOPE.overrides = prev
        _SCOPE.memo = prev_memo
        _SCOPE.sigs = prev_sigs


def _pinned_signature(node: N.PlanNode, sigs: dict) -> str:
    """Subtree signature memoized ACROSS lookup calls within one scope:
    planner join ordering builds fresh candidate trees around shared
    child subtrees, and recomputing every child's repr-normalized
    signature per estimate call would make history-on planning
    quadratic. ``sigs`` maps id -> (node, sig) and keeps the node
    referenced, so a dead node's id can never alias a live one — which
    makes seeding :func:`_signature`'s plain memo from it safe."""
    ent = sigs.get(id(node))
    if ent is not None and ent[0] is node:
        return ent[1]
    plain = {i: s for i, (_n, s) in sigs.items()}
    seeded = set(plain)
    sig = _signature(node, plain)
    for n in N.walk(node):
        i = id(n)
        if i not in seeded and i in plain:
            sigs[i] = (n, plain[i])
    return sig


def lookup_rows(node: N.PlanNode) -> Optional[float]:
    """Observed output rows for ``node``'s canonical sub-fingerprint,
    or None (no active store / no history). The ONE read path
    ``optimizer.estimate_rows`` consults (lint:
    tools/check_history_sites.py). Mid-query runtime observations
    (:func:`with_overrides`) take precedence over the store; an
    active :func:`capture_consults` scope records the evidence every
    consult returned. Never raises — a broken store must degrade to
    classic estimation, not fail planning."""
    store = getattr(_SCOPE, "store", None)
    overrides = getattr(_SCOPE, "overrides", None)
    if store is None and not overrides:
        return None
    try:
        memo = getattr(_SCOPE, "memo", None)
        ent = memo.get(id(node)) if memo is not None else None
        if ent is not None and ent[0] is node:
            fp = ent[1]
        else:
            sigs = getattr(_SCOPE, "sigs", None)
            if sigs is None:
                fp = node_fingerprint(node)
            else:
                fp = _digest(_pinned_signature(node, sigs))
            if memo is not None:
                # keep the node referenced so its id cannot be reused
                memo[id(node)] = (node, fp)
        if overrides:
            got = overrides.get(fp)
            if got is not None:
                return float(got)
        if store is None:
            return None
        got = store.lookup(fp)
        con = getattr(_SCOPE, "consulted", None)
        if con is not None and fp not in con:
            con[fp] = {
                "epoch": store.epoch_of(fp),
                "rows": got,
                "est": None,
            }
        return got
    except Exception:
        return None


def note_estimate(node: N.PlanNode, rows: float) -> None:
    """Record the CLASSIC estimate the optimizer fell back to for a
    consulted node with no history — the base the replan divergence
    test compares the first learned cardinality against
    (``optimizer.estimate_rows`` is the one caller). No active capture
    scope = no-op; never raises."""
    con = getattr(_SCOPE, "consulted", None)
    if con is None:
        return
    try:
        memo = getattr(_SCOPE, "memo", None)
        ent = memo.get(id(node)) if memo is not None else None
        if ent is None or ent[0] is not node:
            return
        cap = con.get(ent[1])
        if (
            cap is not None
            and cap.get("rows") is None
            and cap.get("est") is None
        ):
            cap["est"] = float(rows)
    except Exception:
        pass


def progress_total_rows(
    store: Optional["QueryHistoryStore"], node
) -> Optional[float]:
    """History-observed output cardinality for a running query's plan
    root — the expected-total denominator behind the live progress
    endpoint's ETA (``coordinator.query_progress``). Lives HERE so the
    coordinator never calls :func:`lookup_rows` directly (the
    history-sites confinement rule pins that read path to this module
    and the optimizer). None = no store, no plan, or no history for
    this shape; never raises."""
    if store is None or node is None:
        return None
    try:
        with using(store):
            return lookup_rows(node)
    except Exception:
        return None


# -------------------------------------------------------------- the store


class QueryHistoryStore:
    """Bounded crash-safe on-disk history store: JSONL segments under a
    directory + an in-memory index keyed by canonical statement
    fingerprint, with a derived per-node index keyed by canonical
    sub-fingerprints. One record per completed query (latest record of
    a statement wins)."""

    def __init__(
        self,
        path: str,
        max_entries: int = 256,
        divergence_factor: float = DEFAULT_DIVERGENCE_FACTOR,
    ):
        self.path = path
        self.max_entries = max(int(max_entries), 1)
        #: relative change beyond which a re-learned cardinality bumps
        #: its fingerprint's epoch (tier-1 adaptive.divergence-factor)
        self.divergence_factor = max(float(divergence_factor), 1.0)
        self._seg_entries = max(
            _SEGMENT_ENTRIES_MIN, self.max_entries // 4
        )
        self._lock = threading.Lock()
        #: statement fingerprint -> record dict (insertion = recency)
        self._index: "OrderedDict[str, dict]" = OrderedDict()
        #: node sub-fingerprint -> latest observed output rows
        self._nodes: Dict[str, float] = {}
        #: node sub-fingerprint -> monotonic epoch, bumped when a
        #: record MATERIALLY changes the learned cardinality (first
        #: learn included — new evidence versus no evidence). Never
        #: reset by eviction (monotonicity is the staleness signal);
        #: persisted beside every record ("epochs" field) and restored
        #: at load as the max epoch seen, so a restarted coordinator's
        #: plan-cache entries compare against the SAME epochs they
        #: were validated at.
        self._epochs: Dict[str, int] = {}
        self.hits = 0
        self.misses = 0
        self.writes = 0
        self.evictions = 0
        os.makedirs(path, exist_ok=True)
        self._load()

    # ------------------------------------------------------------ disk

    def _segments(self) -> List[str]:
        try:
            names = sorted(
                f
                for f in os.listdir(self.path)
                if f.startswith("history-") and f.endswith(".jsonl")
            )
        except OSError:
            return []
        return [os.path.join(self.path, f) for f in names]

    def _load(self) -> None:
        """Rebuild the index from surviving segments, oldest first so
        later records win. Torn/corrupt lines (a crash mid-append) are
        skipped — the store must always come back up."""
        max_seq = -1
        for seg in self._segments():
            name = os.path.basename(seg)
            try:
                max_seq = max(
                    max_seq, int(name[len("history-"):-len(".jsonl")])
                )
            except ValueError:
                pass
            try:
                with open(seg, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except Exception:
                            continue  # torn write / bit rot: skip
                        if not isinstance(rec, dict) or "fp" not in rec:
                            continue
                        self._apply(rec)
            except OSError:
                continue
        # next sequence AFTER the largest surviving name (NOT the
        # segment count: GC leaves numbering gaps, and reusing a
        # surviving name would invert replay recency and mis-target
        # GC's keep-newest-names policy). A restart always starts a
        # fresh segment, so _cur_count=0 is exact.
        self._seg_seq = max_seq + 1
        self._cur_count = 0
        self._shrink_index(evict_metric=False)
        self._rebuild_nodes()

    def _apply(self, rec: dict) -> None:
        fp = rec["fp"]
        self._index[fp] = rec
        self._index.move_to_end(fp)
        # epoch restore: records persist the epoch each node carried
        # when written; max() keeps monotonicity over replay order
        # (older checkpoint copies must never roll a newer epoch back)
        for nfp, ep in (rec.get("epochs") or {}).items():
            try:
                ep = int(ep)
            except (TypeError, ValueError):
                continue
            if ep > self._epochs.get(nfp, 0):
                self._epochs[nfp] = ep

    def _shrink_index(self, evict_metric: bool = True) -> int:
        from presto_tpu.utils.metrics import REGISTRY

        evicted = 0
        while len(self._index) > self.max_entries:
            self._index.popitem(last=False)
            evicted += 1
            if evict_metric:
                self.evictions += 1
                REGISTRY.counter("history.evict").update()
        return evicted

    def _rebuild_nodes(self) -> None:
        self._nodes = {}
        for rec in self._index.values():
            for nfp, nd in (rec.get("nodes") or {}).items():
                try:
                    self._nodes[nfp] = float(nd["rows"])
                except (KeyError, TypeError, ValueError):
                    pass

    def _cur_segment(self) -> str:
        return os.path.join(
            self.path, f"history-{self._seg_seq:06d}.jsonl"
        )

    def _gc_segments(self) -> None:
        """Delete all but the newest two segments. Safe because a
        rotation opens each new segment with a full checkpoint of the
        live index, so the newest segment alone replays every index
        entry (counting retained LINES instead would let a hot
        statement's duplicates crowd out the only on-disk copy of
        colder entries); the previous segment is kept in case a crash
        tore the newest checkpoint mid-write."""
        segs = self._segments()
        for seg in segs[:-2]:
            try:
                os.unlink(seg)
            except OSError:
                pass

    # ------------------------------------------------------------- write

    def record_query(
        self,
        stmt_fp: str,
        sql: str,
        nodes: Dict[str, dict],
    ) -> None:
        """Persist one completed query's per-node actuals. ``nodes``
        maps canonical sub-fingerprint -> {"rows": int, "label": str}.
        Crash-safe: one JSON line, flushed; a torn line is skipped at
        the next load."""
        from presto_tpu.utils.metrics import REGISTRY

        if not stmt_fp or not nodes:
            return
        rec = {
            "fp": stmt_fp,
            "query": (sql or "")[:500],
            "ts": time.time(),
            "nodes": nodes,
        }
        with self._lock:
            # epoch plane FIRST (so the record persists the bumped
            # epochs): a record that MATERIALLY changes a learned
            # cardinality (or learns one for the first time) bumps the
            # node's epoch — the cheap staleness signal plan-cache
            # entries compare against. Small drift keeps the epoch:
            # noise must not invalidate every warm plan.
            for nfp, nd in nodes.items():
                try:
                    new_rows = float(nd["rows"])
                except (KeyError, TypeError, ValueError):
                    continue
                prev_rows = self._nodes.get(nfp)
                if prev_rows is None or diverged(
                    prev_rows, new_rows, self.divergence_factor
                ):
                    self._epochs[nfp] = self._epochs.get(nfp, 0) + 1
            # the epoch rides the record to disk: a restarted
            # coordinator restores it at load instead of serving
            # cold-epoch cache hits (epoch 0) against warm plans
            rec["epochs"] = {
                nfp: self._epochs.get(nfp, 0) for nfp in nodes
            }
            line = json.dumps(rec, default=str)
            rotate = self._cur_count >= self._seg_entries
            if rotate:
                self._seg_seq += 1
                self._cur_count = 0
            try:
                with open(self._cur_segment(), "a", encoding="utf-8") as f:
                    if rotate:
                        # compaction checkpoint: the fresh segment
                        # opens with a snapshot of the live index, so
                        # every entry stays replayable once GC drops
                        # the older segments — epochs refreshed to
                        # CURRENT (a record's stored epoch may predate
                        # later bumps; the checkpoint must not replay
                        # a rollback)
                        for old in self._index.values():
                            if old.get("fp") != stmt_fp:
                                dup = dict(old)
                                dup["epochs"] = {
                                    nfp: self._epochs.get(nfp, 0)
                                    for nfp in (old.get("nodes") or {})
                                }
                                f.write(
                                    json.dumps(dup, default=str) + "\n"
                                )
                    f.write(line + "\n")
                    f.flush()
                self._cur_count += 1
                if rotate:
                    self._gc_segments()
            except OSError:
                pass  # a full/broken disk must never fail the query
            prev = self._index.get(stmt_fp)
            self._apply(rec)
            evicted = self._shrink_index()
            if evicted or (
                prev is not None
                and set(prev.get("nodes") or {}) != set(nodes)
            ):
                # an evicted (or shape-shifted) record may own node
                # keys no surviving record covers — rebuild
                self._rebuild_nodes()
            else:
                # common warm path: fold just this record's nodes
                # instead of re-deriving the whole index under the
                # lock planner-side lookup() contends on
                for nfp, nd in nodes.items():
                    try:
                        self._nodes[nfp] = float(nd["rows"])
                    except (KeyError, TypeError, ValueError):
                        pass
            self.writes += 1
        REGISTRY.counter("history.write").update()

    def query_completed(self, event) -> None:
        """Query-completed listener hook: the store's write path is the
        SAME path as the JSONL event sink (exec/stats.QueryHistory).
        Only successful queries record — a failed run's partial row
        counts would poison the learned cardinalities."""
        qs = event.stats
        if qs.error is not None:
            return
        fp = getattr(qs, "plan_fingerprint", "")
        ops = (
            qs.all_operator_stats()
            if hasattr(qs, "all_operator_stats")
            else getattr(qs, "operators", None) or []
        )
        nodes = {
            op.fingerprint: {
                "rows": int(op.output_rows),
                "label": op.label,
            }
            for op in ops
            if op.fingerprint and op.output_rows >= 0
        }
        self.record_query(fp, qs.sql, nodes)

    # -------------------------------------------------------------- read

    def lookup(self, fp: str) -> Optional[float]:
        from presto_tpu.utils.metrics import REGISTRY

        with self._lock:
            got = self._nodes.get(fp)
            if got is None:
                self.misses += 1
            else:
                self.hits += 1
        if got is None:
            REGISTRY.counter("history.miss").update()
            return None
        REGISTRY.counter("history.hit").update()
        return got

    def epoch_of(self, fp: str) -> int:
        """Current epoch of one node fingerprint (0 = never learned /
        never materially changed in this process). Metric-silent: the
        plan-cache staleness check must not skew history.hit/miss."""
        with self._lock:
            return self._epochs.get(fp, 0)

    def learned_rows(self, fp: str) -> Optional[float]:
        """Latest learned cardinality for one node fingerprint,
        metric-silent (the replan seam's read — see ``lookup`` for
        the counted estimate-time path)."""
        with self._lock:
            return self._nodes.get(fp)

    # ----------------------------------------------------- introspection

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._index),
                "capacity": self.max_entries,
                "nodes": len(self._nodes),
                "hits": self.hits,
                "misses": self.misses,
                "writes": self.writes,
                "evictions": self.evictions,
            }

    def snapshot(self) -> List[dict]:
        """Rows for the ``system.runtime.query_history`` view."""
        with self._lock:
            out = []
            for rec in self._index.values():
                nodes = rec.get("nodes") or {}
                out.append(
                    {
                        "fingerprint": rec.get("fp", ""),
                        "query": rec.get("query", ""),
                        "node_count": len(nodes),
                        "total_rows": sum(
                            int(n.get("rows", 0)) for n in nodes.values()
                        ),
                        # adaptive-execution staleness signal: the
                        # newest epoch among this statement's recorded
                        # operator fingerprints (the statement-level
                        # view of what epoch-versioned plan-cache
                        # entries are judged by)
                        "epoch": max(
                            (
                                self._epochs.get(nfp, 0)
                                for nfp in nodes
                            ),
                            default=0,
                        ),
                        "updated": float(rec.get("ts", 0.0)),
                    }
                )
            return out
