"""Null source/sink with configurable fake row counts (reference:
``presto-blackhole``, SURVEY.md §2.2 — scheduler/perf test fixture)."""

from __future__ import annotations

import time
from typing import Dict, Sequence

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.spi import (
    Connector,
    ConnectorMetadata,
    ConnectorSplit,
    SplitSource,
    TableHandle,
    TableStats,
)


class _BhMetadata(ConnectorMetadata):
    def __init__(self, tables):
        self._tables = tables

    def list_schemas(self):
        return ["default"]

    def list_tables(self, schema):
        return sorted(t for _, t in self._tables)

    def get_table_schema(self, handle: TableHandle):
        return dict(self._tables[(handle.schema, handle.table)]["schema"])

    def get_table_stats(self, handle: TableHandle):
        return TableStats(
            row_count=float(self._tables[(handle.schema, handle.table)]["rows"])
        )


class BlackholeConnector(Connector):
    """Tables are declared via create_table with extra config keys:
    rows_per_table and page_processing_delay_s (fault/latency injection,
    SURVEY.md §5.3)."""

    def __init__(self, rows_per_table: int = 0, delay_s: float = 0.0, **config):
        self._tables: Dict[tuple, dict] = {}
        self._default_rows = rows_per_table
        self._delay_s = delay_s
        self._metadata = _BhMetadata(self._tables)

    def metadata(self):
        return self._metadata

    def supports_writes(self):
        return True

    def create_table(self, handle: TableHandle, schema, rows: int = None):
        self._tables[(handle.schema, handle.table)] = {
            "schema": dict(schema),
            "rows": self._default_rows if rows is None else rows,
        }

    def append_rows(self, handle, data):
        pass  # the sink half: swallow everything

    def get_splits(self, handle: TableHandle, target_split_rows: int = 1 << 20, constraint=()):
        n = self._tables[(handle.schema, handle.table)]["rows"]
        splits = [
            ConnectorSplit(handle, lo, min(lo + target_split_rows, n))
            for lo in range(0, n, target_split_rows)
        ] or [ConnectorSplit(handle, 0, 0)]
        return SplitSource(splits)

    def create_page_source(self, split: ConnectorSplit, columns: Sequence[str]):
        if self._delay_s:
            time.sleep(self._delay_s)
        schema = self._tables[(split.table.schema, split.table.table)]["schema"]
        n = split.num_rows
        out = {}
        for c in columns:
            t = schema[c]
            if t.is_string:
                from presto_tpu.connectors.tpch import DictColumn

                out[c] = DictColumn(
                    ids=np.zeros(n, dtype=np.int32),
                    values=np.asarray(["x"], dtype=object),
                )
            else:
                out[c] = np.zeros(n, dtype=t.np_dtype)
        return out
