"""Built-in ``system`` catalog.

Reference parity: ``presto-system``'s runtime tables
(``system.runtime.queries``, ``system.runtime.tasks``,
``system.runtime.nodes``) and the jmx-connector pattern of making
engine metrics SQL-able (SURVEY.md §5.5). Backed live by the runner's
QueryHistory and the process metrics registry — zero stored bytes.
"""

from __future__ import annotations

import json
from typing import Dict, Sequence

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.spi import (
    Connector,
    ConnectorMetadata,
    ConnectorSplit,
    SplitSource,
    TableHandle,
)

_SCHEMAS: Dict[str, Dict[str, Dict[str, T.DataType]]] = {
    "runtime": {
        "queries": {
            "query_id": T.VARCHAR,
            "state": T.VARCHAR,
            "query": T.VARCHAR,
            "trace_id": T.VARCHAR,
            "plan_fingerprint": T.VARCHAR,
            "elapsed_ms": T.DOUBLE,
            "planning_ms": T.DOUBLE,
            "optimization_ms": T.DOUBLE,
            "staging_ms": T.DOUBLE,
            "execution_ms": T.DOUBLE,
            "compile_cache_hit": T.BOOLEAN,
            # micro-batched serving: answered by a shared vmapped
            # dispatch (QueryStats.batched)
            "batched": T.BOOLEAN,
            # serving-plane result reuse: answered from the snapshot-
            # keyed result cache (fresh or bounded-stale serve)
            "cached": T.BOOLEAN,
            "retries": T.BIGINT,
            "input_rows": T.BIGINT,
            "input_bytes": T.BIGINT,
            "output_rows": T.BIGINT,
            "error": T.VARCHAR,
        },
        "query_history": {
            "fingerprint": T.VARCHAR,
            "query": T.VARCHAR,
            "node_count": T.BIGINT,
            "total_rows": T.BIGINT,
            # adaptive execution: the statement fingerprint's history
            # epoch (bumped on material cardinality change; the signal
            # epoch-versioned plan-cache entries are judged by)
            "epoch": T.BIGINT,
            "updated": T.DOUBLE,
        },
        "nodes": {
            "node_id": T.VARCHAR,
            "http_uri": T.VARCHAR,
            "node_version": T.VARCHAR,
            "coordinator": T.BOOLEAN,
            "state": T.VARCHAR,
            # elastic pools: preemptible capacity flag, the node's pool
            # lifecycle state, and (coordinator row) the autoscaler's
            # last decision
            "preemptible": T.BOOLEAN,
            "pool_state": T.VARCHAR,
            "last_decision": T.VARCHAR,
            # boot-time device probe (utils/devicediag.py), JSON: the
            # failing phase, error class, and fallback decision — a
            # silently CPU-degraded node is visible from SQL
            "backend_diag": T.VARCHAR,
        },
        "tasks": {
            "query_id": T.VARCHAR,
            "stage_id": T.BIGINT,
            "task_id": T.VARCHAR,
            "node_id": T.VARCHAR,
            "state": T.VARCHAR,
            "wall_ms": T.DOUBLE,
            "staging_ms": T.DOUBLE,
            "execute_ms": T.DOUBLE,
            "input_rows": T.BIGINT,
            "input_bytes": T.BIGINT,
            "output_rows": T.BIGINT,
            "output_bytes": T.BIGINT,
            "retries": T.BIGINT,
        },
        "metrics": {
            "name": T.VARCHAR,
            "kind": T.VARCHAR,
            "value": T.DOUBLE,
        },
        # time-series view over the coordinator's telemetry sampler
        # (utils/telemetry.MetricsSampler; telemetry.sample-interval-s
        # enables it): one row per retained (node, metric) sample with
        # the rate against the stream's previous observation
        "metrics_history": {
            "node": T.VARCHAR,
            "ts": T.DOUBLE,
            "name": T.VARCHAR,
            "value": T.DOUBLE,
            "rate": T.DOUBLE,
        },
        # materialized views (exec/mview.py): definition, base table,
        # tip snapshot, and how/when the view was last maintained
        "materialized_views": {
            "view": T.VARCHAR,
            "base_table": T.VARCHAR,
            "eligible": T.BOOLEAN,
            "reason": T.VARCHAR,
            "snapshot_id": T.BIGINT,
            "last_refresh_mode": T.VARCHAR,
            "refresh_age_s": T.DOUBLE,
            "refreshes": T.BIGINT,
            "incremental_refreshes": T.BIGINT,
            "rows": T.BIGINT,
        },
        "caches": {
            "cache": T.VARCHAR,
            "entries": T.BIGINT,
            "bytes": T.BIGINT,
            "budget_bytes": T.BIGINT,
            "hits": T.BIGINT,
            "misses": T.BIGINT,
            "evictions": T.BIGINT,
        },
        # tail-latency QoS plane (server/qos.py): one row per
        # admission lane member — priority, SLO target, live
        # running/queued/suspended occupancy, p50/p99 latency
        # reservoir, and suspension/resume/SLO-miss counters
        "qos": {
            "group": T.VARCHAR,
            "priority": T.BIGINT,
            "target_p99_ms": T.DOUBLE,
            "queries": T.BIGINT,
            "running": T.BIGINT,
            "queued": T.BIGINT,
            "suspended": T.BIGINT,
            "p50_ms": T.DOUBLE,
            "p99_ms": T.DOUBLE,
            "slo_misses": T.BIGINT,
            "suspensions": T.BIGINT,
            "resumes": T.BIGINT,
        },
        # durable lakehouse (server/manifests.py): one row per
        # manifest-committed table — tip snapshot id, retained
        # snapshot count, live file/byte/row footprint, and whether
        # the tip is a compaction ('compacted'), compaction is due
        # ('pending'), or neither ('none')
        "snapshots": {
            "table": T.VARCHAR,
            "snapshot_id": T.BIGINT,
            "snapshots": T.BIGINT,
            "files": T.BIGINT,
            "bytes": T.BIGINT,
            "rows": T.BIGINT,
            "compaction": T.VARCHAR,
        },
        # cluster memory governance (server/memory_arbiter.py): one
        # row per node (query_id '') + one per (node, query) holder,
        # plus KILLED rows for the arbiter's victim decisions
        "memory": {
            "node_id": T.VARCHAR,
            "query_id": T.VARCHAR,
            "state": T.VARCHAR,
            "reserved_bytes": T.BIGINT,
            "peak_bytes": T.BIGINT,
            "blocked_bytes": T.BIGINT,
            "spilled_bytes": T.BIGINT,
            "limit_bytes": T.BIGINT,
        },
    },
    "metadata": {
        "catalogs": {"catalog_name": T.VARCHAR, "connector_id": T.VARCHAR},
    },
}


class _SystemMetadata(ConnectorMetadata):
    def list_schemas(self):
        return sorted(_SCHEMAS)

    def list_tables(self, schema):
        return sorted(_SCHEMAS.get(schema, {}))

    def get_table_schema(self, handle: TableHandle):
        try:
            return dict(_SCHEMAS[handle.schema][handle.table])
        except KeyError:
            raise KeyError(
                f"table not found: system.{handle.schema}.{handle.table}"
            )


class SystemConnector(Connector):
    """Catalog ``system``: live engine introspection tables."""

    def __init__(self, runner=None, **config):
        self._runner = runner
        self._metadata = _SystemMetadata()

    def metadata(self):
        return self._metadata

    def cacheable(self):
        return False  # live data: never reuse staged pages

    def coordinator_only(self):
        return True  # workers' system tables are empty: never distribute

    def get_splits(self, handle: TableHandle, target_split_rows: int = 1 << 20, constraint=()):
        return SplitSource([ConnectorSplit(handle, 0, 0)])

    def create_page_source(self, split: ConnectorSplit, columns: Sequence[str]):
        rows = self._rows(split.table)
        return {
            c: np.array([r[c] for r in rows], dtype=object) for c in columns
        }

    # ------------------------------------------------------------- tables

    def _rows(self, handle: TableHandle):
        key = (handle.schema, handle.table)
        if key == ("runtime", "queries"):
            hist = self._runner.history.snapshot() if self._runner else []
            return [
                {
                    "query_id": q.query_id,
                    "state": q.state,
                    "query": q.sql.strip(),
                    "trace_id": q.trace_id,
                    "plan_fingerprint": q.plan_fingerprint,
                    "elapsed_ms": q.elapsed_ms,
                    "planning_ms": q.planning_ms,
                    "optimization_ms": q.optimization_ms,
                    "staging_ms": q.staging_ms,
                    "execution_ms": q.execution_ms,
                    "compile_cache_hit": q.compile_cache_hit,
                    "batched": q.batched,
                    "cached": q.result_cache in ("hit", "stale"),
                    "retries": q.retries,
                    "input_rows": q.input_rows,
                    "input_bytes": q.input_bytes,
                    "output_rows": q.output_rows,
                    "error": q.error,
                }
                for q in hist
            ]
        if key == ("runtime", "nodes"):
            return self._node_rows()
        if key == ("runtime", "tasks"):
            return self._task_rows()
        if key == ("runtime", "metrics"):
            from presto_tpu.utils.metrics import REGISTRY

            return [
                {"name": n, "kind": k, "value": v}
                for n, k, v in REGISTRY.snapshot()
            ]
        if key == ("runtime", "metrics_history"):
            cluster = getattr(self._runner, "cluster", None)
            sampler = (
                getattr(cluster, "telemetry_sampler", None)
                if cluster
                else None
            )
            # sampler off (or plain local runner): empty view, not an
            # error — same contract as the qos view
            return sampler.rows() if sampler is not None else []
        if key == ("runtime", "caches"):
            return self._cache_rows()
        if key == ("runtime", "materialized_views"):
            reg = getattr(self._runner, "_mview_registry", None)
            return reg.view_rows() if reg is not None else []
        if key == ("runtime", "memory"):
            return self._memory_rows()
        if key == ("runtime", "qos"):
            cluster = getattr(self._runner, "cluster", None)
            qos = getattr(cluster, "qos", None) if cluster else None
            # plane off (or plain local runner): an empty view, not an
            # error — dashboards can always select from it
            return qos.view_rows() if qos is not None else []
        if key == ("runtime", "snapshots"):
            return self._snapshot_rows()
        if key == ("runtime", "query_history"):
            store = getattr(self._runner, "history_store", None)
            return store.snapshot() if store is not None else []
        if key == ("metadata", "catalogs"):
            names = self._runner.catalogs.names() if self._runner else []
            return [
                {
                    "catalog_name": n,
                    "connector_id": type(
                        self._runner.catalogs.get(n)
                    ).__name__,
                }
                for n in names
            ]
        raise KeyError(f"system table {handle.schema}.{handle.table}")

    def _task_rows(self):
        """Per-task stats of distributed queries (reference:
        system.runtime.tasks), from the embedding coordinator's stage
        rollups; empty on a plain local runner. Retention follows the
        coordinator's bounded query map (MAX_QUERY_HISTORY completed
        queries) — tasks age out with their query."""
        cluster = getattr(self._runner, "cluster", None)
        if cluster is None:
            return []
        out = []
        for q in list(cluster.queries.values()):
            for stage in q.stats.stages:
                for t in list(stage.tasks):
                    out.append(
                        {
                            "query_id": t.query_id,
                            "stage_id": stage.stage_id,
                            "task_id": t.task_id,
                            "node_id": t.node_id,
                            "state": t.state,
                            "wall_ms": t.wall_ms,
                            "staging_ms": t.staging_ms,
                            "execute_ms": t.execute_ms,
                            "input_rows": t.input_rows,
                            "input_bytes": t.input_bytes,
                            "output_rows": t.output_rows,
                            "output_bytes": t.output_bytes,
                            "retries": t.retries,
                        }
                    )
        return out

    def _snapshot_rows(self):
        """Per-table tip state of every mounted manifest store
        (server/manifests.py): the ingest lane's store plus any
        lakehouse-configured file connector, deduplicated by root —
        the common deployment points them at the SAME directory.
        Empty when no lakehouse is configured (plain WAL ingest or
        no ingest at all): a view, never an error."""
        if self._runner is None:
            return []
        stores = {}
        ing = getattr(self._runner, "ingest", None)
        store = getattr(ing, "store", None)
        if store is not None:
            stores[store.root] = store
        for name in self._runner.catalogs.names():
            conn = self._runner.catalogs.get(name)
            cstore = getattr(conn, "manifest_store", None)
            if cstore is not None:
                stores.setdefault(cstore.root, cstore)
        out = []
        for store in stores.values():
            for tk in store.tables():
                try:
                    out.append(store.table_stats(tk))
                except OSError:
                    continue  # torn directory mid-GC: skip the row
        out.sort(key=lambda r: r["table"])
        return out

    def _cache_rows(self):
        """Live occupancy of the engine caches (reference: the jmx
        cache-stats beans): the device-resident split cache (staged
        pages, LRU byte budget) and the compiled-program cache."""
        if self._runner is None:
            return []
        from presto_tpu.utils.metrics import REGISTRY

        split = self._runner.split_cache.stats()
        rows = [
            {
                "cache": "staging.split_cache",
                "entries": split["entries"],
                "bytes": split["bytes"],
                "budget_bytes": split["budget_bytes"],
                "hits": split["hits"],
                "misses": split["misses"],
                "evictions": split["evictions"],
            },
            {
                "cache": "compile.programs",
                "entries": len(self._runner._compiled),
                "bytes": 0,  # XLA owns the executables; not accounted
                "budget_bytes": 0,
                # process-global counters (the bench's amortization
                # signal), beside this runner's entry count
                "hits": int(
                    REGISTRY.counter("compile.cache_hit").total
                ),
                "misses": int(
                    REGISTRY.counter("compile.cache_miss").total
                ),
                "evictions": 0,
            },
        ]
        # statement-level parameterized plan cache (plan/canonical.py):
        # occupancy + this runner's hit/miss/evict tallies beside the
        # staging and compile rows
        pc = getattr(self._runner, "plan_cache", None)
        if pc is not None:
            s = pc.stats()
            rows.append(
                {
                    "cache": "plan.cache",
                    "entries": s["entries"],
                    "bytes": 0,  # plans are small host objects
                    "budget_bytes": 0,
                    "hits": s["hits"],
                    "misses": s["misses"],
                    "evictions": s["evictions"],
                }
            )
        # serving-plane result cache (server/result_cache.py): the
        # snapshot-keyed entries the coordinator serves without
        # planning or dispatch (attached by the embedding coordinator;
        # None on plain runners)
        rc = getattr(self._runner, "result_cache", None)
        if rc is not None:
            s = rc.stats()
            rows.append(
                {
                    "cache": "result.cache",
                    "entries": s["entries"],
                    "bytes": s["bytes"],
                    "budget_bytes": s["budget_bytes"],
                    "hits": s["hits"],
                    "misses": s["misses"],
                    "evictions": s["evictions"],
                }
            )
        # host-spill pool (cluster memory governance): device pages
        # offloaded to host RAM under HBM pressure; hits = restages
        rows.append(
            {
                "cache": "staging.host_spill",
                "entries": split.get("spill_entries", 0),
                "bytes": split.get("spill_bytes", 0),
                "budget_bytes": split.get("spill_budget_bytes", 0),
                "hits": split.get("restages", 0),
                "misses": 0,
                "evictions": split.get("spills", 0),
            }
        )
        # streaming-ingest WAL occupancy (server/ingest.py): pending
        # (durable, not yet committed) batches, WAL bytes written,
        # committed folds as hits, replayed tail batches as evictions
        ingest = getattr(self._runner, "ingest", None)
        if ingest is not None:
            s = ingest.stats()
            rows.append(
                {
                    "cache": "ingest.wal",
                    "entries": s["pending_batches"],
                    "bytes": s["wal_bytes"],
                    "budget_bytes": 0,
                    "hits": s["commits"],
                    "misses": 0,
                    "evictions": s["replayed"],
                }
            )
        # in-slice exchange segment (server/exchange_spi.py): device-
        # resident partitioned output parked for co-located consumers.
        # hits = ICI edges served, misses = planned-ICI fetches that
        # fell back to the wire, evictions = drain/retry
        # materializations to HTTP — the win is observable, not
        # asserted
        from presto_tpu.server.exchange_spi import SEGMENT

        seg = SEGMENT.stats()
        rows.append(
            {
                "cache": "exchange.ici",
                "entries": seg["entries"],
                "bytes": seg["bytes"],
                "budget_bytes": 0,  # bounded by the MemoryPool
                "hits": seg["hits"],
                "misses": seg["misses"],
                "evictions": int(
                    REGISTRY.counter("exchange.ici_materialized").total
                ),
            }
        )
        # durable-exchange spool occupancy (fault-tolerant execution):
        # present when the embedding coordinator has exchange.spool-path
        # configured (server.spool shares the directory with workers)
        cluster = getattr(self._runner, "cluster", None)
        spool = getattr(cluster, "spool", None) if cluster else None
        if spool is not None:
            s = spool.stats()
            rows.append(
                {
                    "cache": "exchange.spool",
                    "entries": s["entries"],
                    "bytes": s["bytes"],
                    "budget_bytes": s["budget_bytes"],
                    "hits": s["hits"],
                    "misses": s["misses"],
                    "evictions": s["evictions"],
                }
            )
        return rows

    def _memory_rows(self):
        """Cluster memory plane (reference: system.memory — per-node
        pool occupancy): the coordinator's arbiter serves the folded
        per-node/per-query view plus its kill decisions; a plain local
        runner serves its own pool's snapshot."""
        cluster = getattr(self._runner, "cluster", None)
        arbiter = getattr(cluster, "arbiter", None) if cluster else None
        if arbiter is not None:
            return arbiter.view_rows()
        pool = getattr(self._runner, "memory_pool", None)
        if pool is None:
            return []
        snap = pool.snapshot()
        cache = getattr(self._runner, "split_cache", None)
        spilled = cache.spill_used_bytes() if cache is not None else 0
        rows = [
            {
                "node_id": "local",
                "query_id": "",
                "state": "BLOCKED" if snap["blocked"] else "OK",
                "reserved_bytes": snap["reserved"],
                "peak_bytes": max(
                    snap["peak"].values(), default=0
                ),
                "blocked_bytes": sum(
                    b["bytes"] for b in snap["blocked"]
                ),
                "spilled_bytes": spilled,
                "limit_bytes": snap["limit"],
            }
        ]
        for owner, nbytes in sorted(snap["used"].items()):
            rows.append(
                {
                    "node_id": "local",
                    "query_id": owner,
                    "state": "RESERVED",
                    "reserved_bytes": nbytes,
                    "peak_bytes": snap["peak"].get(owner, nbytes),
                    "blocked_bytes": 0,
                    "spilled_bytes": 0,
                    "limit_bytes": snap["limit"],
                }
            )
        return rows

    def _node_rows(self):
        cluster = getattr(self._runner, "cluster", None)
        if cluster is not None:
            pool_state = getattr(cluster, "pool_state", None)
            decision = getattr(cluster, "pool_decision", "")
            return [
                {
                    "node_id": w.node_id,
                    "http_uri": w.uri,
                    "node_version": w.version,
                    "coordinator": w.coordinator,
                    "state": w.state,
                    "preemptible": bool(
                        getattr(w, "preemptible", False)
                    ),
                    "pool_state": (
                        pool_state(w)
                        if pool_state is not None
                        else "STABLE"
                    ),
                    # the autoscaler is a coordinator duty: its last
                    # decision renders on the coordinator row only
                    "last_decision": (
                        decision if w.coordinator else ""
                    ),
                    "backend_diag": json.dumps(
                        getattr(w, "backend_diag", {}) or {}
                    ),
                }
                for w in cluster.nodes()
            ]
        import jax

        from presto_tpu.utils.devicediag import last_diag_dict

        return [
            {
                "node_id": "local",
                "http_uri": "local://",
                "node_version": "presto-tpu-0.1",
                "coordinator": True,
                "state": f"ACTIVE ({len(jax.devices())} devices)",
                "preemptible": False,
                "pool_state": "STABLE",
                "last_decision": "",
                "backend_diag": json.dumps(last_diag_dict()),
            }
        ]
