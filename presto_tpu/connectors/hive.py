"""Hive-style connector: partitioned directories of parquet files.

Reference parity: ``presto-hive``'s core read surface (SURVEY.md §2.2
"production connectors") — a table is a DIRECTORY of files, optionally
nested in ``key=value`` partition directories whose path components are
real (virtual) columns:

    root/<schema>/<table>/[<k1>=<v1>/[<k2>=<v2>/...]]part-*.parquet

TPU-first shape: identical engine contract to the single-file parquet
connector — splits are ranges of ONE global row space (files get
contiguous ranges in sorted-path order, so the split protocol stays
format- and layout-agnostic), payloads are device-ready columns, and
partition-key columns materialize as constant dictionary/numeric
columns per file (zero bytes read for them).

Partition-key typing: a ``metastore.json`` at the connector root
declares key types per table (the reference's Hive Metastore as a
file — SURVEY.md §2.2 "metastore-backed schemas"):

    {"schemas": {"<schema>": {"<table>":
        {"partition_keys": {"year": "integer", "d": "date"}}}}}

Without a declaration the engine INFERS: a key whose every observed
value parses as an integer is BIGINT, everything else VARCHAR (a
documented fallback, matching the pre-metastore behavior).

No predicate pushdown into partition enumeration yet: partition columns
filter like ordinary columns (correct; enumeration-time pruning is a
later optimization and this SPI's splits carry no predicates).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors._arrow import (
    arrow_column_to_payload,
    arrow_to_engine_type,
)
from presto_tpu.connectors.spi import (
    ColumnStats,
    Connector,
    ConnectorMetadata,
    ConnectorSplit,
    RangeSet,
    SplitSource,
    TableHandle,
    TableStats,
)
from presto_tpu.connectors.tpch import DictColumn


class _HiveFile:
    """One data file + its partition-path key values."""

    __slots__ = ("path", "keys", "row_start", "row_end", "pf")

    def __init__(self, path: str, keys: Dict[str, str]):
        self.path = path
        self.keys = keys
        self.row_start = 0
        self.row_end = 0
        self.pf = None  # lazy pyarrow.parquet.ParquetFile


def _is_int(s: str) -> bool:
    try:
        int(s)
        return True
    except ValueError:
        return False


class _HiveMetadata(ConnectorMetadata):
    def __init__(self, conn: "HiveConnector"):
        self._conn = conn

    def list_schemas(self) -> List[str]:
        root = self._conn.root
        return sorted(
            d
            for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        )

    def list_tables(self, schema: str) -> List[str]:
        d = os.path.join(self._conn.root, schema)
        return sorted(
            t for t in os.listdir(d) if os.path.isdir(os.path.join(d, t))
        )

    def get_table_schema(self, handle: TableHandle) -> Dict[str, T.DataType]:
        cached = self._conn._schemas.get(handle)
        if cached is not None:
            return dict(cached)
        files, part_types = self._conn._layout(handle)
        if not files:
            raise KeyError(f"hive table {handle.table} has no files")
        pf = self._conn._file(files[0])
        schema = {
            f.name: arrow_to_engine_type(f.type)
            for f in pf.schema_arrow
        }
        schema.update(part_types)
        self._conn._schemas[handle] = schema
        return dict(schema)

    def get_table_stats(self, handle: TableHandle) -> TableStats:
        files, part_types = self._conn._layout(handle)
        total = 0.0
        mins: Dict[str, float] = {}
        maxs: Dict[str, float] = {}
        for f in files:
            md = self._conn._file(f).metadata
            total += md.num_rows
            for rg in range(md.num_row_groups):
                g = md.row_group(rg)
                for ci in range(g.num_columns):
                    c = g.column(ci)
                    st = c.statistics
                    if st is None or not st.has_min_max:
                        continue
                    if not isinstance(st.min, (int, float)):
                        continue
                    name = c.path_in_schema
                    mins[name] = min(
                        mins.get(name, st.min), st.min
                    )
                    maxs[name] = max(
                        maxs.get(name, st.max), st.max
                    )
        cols = {
            name: ColumnStats(
                min_value=float(mins[name]), max_value=float(maxs[name])
            )
            for name in mins
        }
        return TableStats(row_count=total, columns=cols)


class HiveConnector(Connector):
    """Catalog over hive-layout directories of parquet files."""

    def prunes_splits(self) -> bool:
        return True  # partition-key constraints skip directories

    def __init__(self, root: str = ".", **config):
        self.root = root
        self._metadata = _HiveMetadata(self)
        self._layouts: Dict[TableHandle, tuple] = {}
        self._schemas: Dict[TableHandle, Dict[str, T.DataType]] = {}
        self._metastore = self._load_metastore()

    def _load_metastore(self) -> dict:
        """Parse ``metastore.json`` at the root (absent = empty)."""
        import json

        path = os.path.join(self.root, "metastore.json")
        if not os.path.isfile(path):
            return {}
        with open(path) as f:
            doc = json.load(f)
        return doc.get("schemas", {})

    def _declared_keys(
        self, handle: TableHandle
    ) -> Optional[Dict[str, T.DataType]]:
        """Declared partition-key types for a table, or None."""
        tbl = self._metastore.get(handle.schema, {}).get(handle.table)
        if not tbl:
            return None
        keys = tbl.get("partition_keys")
        if not keys:
            return None
        return {k: T.parse_type(v) for k, v in keys.items()}

    def metadata(self):
        return self._metadata

    def _layout(
        self, handle: TableHandle
    ) -> Tuple[List[_HiveFile], Dict[str, T.DataType]]:
        """Enumerate the table's files (sorted-path order => one stable
        global row space) + inferred partition-key types."""
        cached = self._layouts.get(handle)
        if cached is not None:
            return cached
        base = os.path.join(self.root, handle.schema, handle.table)
        if not os.path.isdir(base):
            raise KeyError(f"no hive table directory at {base}")
        files: List[_HiveFile] = []
        key_values: Dict[str, List[str]] = {}
        for dirpath, _dirnames, filenames in sorted(os.walk(base)):
            rel = os.path.relpath(dirpath, base)
            keys: Dict[str, str] = {}
            if rel != ".":
                for comp in rel.split(os.sep):
                    if "=" not in comp:
                        raise ValueError(
                            f"non-partition directory {comp!r} under "
                            f"{base} (expected key=value)"
                        )
                    k, v = comp.split("=", 1)
                    keys[k] = v
            for fn in sorted(filenames):
                if fn.endswith(".parquet"):
                    f = _HiveFile(os.path.join(dirpath, fn), keys)
                    files.append(f)
                    for k, v in keys.items():
                        key_values.setdefault(k, []).append(v)
        lo = 0
        for f in files:
            n = self._file(f).metadata.num_rows
            f.row_start, f.row_end = lo, lo + n
            lo += n
        declared = self._declared_keys(handle)
        if declared is not None:
            # metastore wins: strict agreement between declaration and
            # the on-disk layout (like the reference failing a table
            # whose partitions don't match the metastore)
            if set(declared) != set(key_values) and key_values:
                raise ValueError(
                    f"metastore declares partition keys "
                    f"{sorted(declared)} but the layout under {base} "
                    f"has {sorted(key_values)}"
                )
            part_types = dict(declared)
        else:
            part_types = {
                k: (
                    T.BIGINT
                    if all(_is_int(v) for v in vs)
                    else T.VARCHAR
                )
                for k, vs in key_values.items()
            }
        # mixed-depth layouts (a file missing a key seen elsewhere)
        # fail HERE with a layout error, not mid-scan with a KeyError
        for f in files:
            missing = set(part_types) - set(f.keys)
            if missing:
                raise ValueError(
                    f"hive layout error: {f.path} lacks partition "
                    f"key(s) {sorted(missing)} present elsewhere "
                    f"under {base}"
                )
        self._layouts[handle] = (files, part_types)
        return files, part_types

    def _file(self, f: _HiveFile):
        import pyarrow.parquet as pq

        if f.pf is None:
            f.pf = pq.ParquetFile(f.path)
        return f.pf

    def get_splits(
        self,
        handle: TableHandle,
        target_split_rows: int = 1 << 20,
        constraint=(),
    ) -> SplitSource:
        """File-aligned splits over the global row space (big files
        split further at row-group-sized boundaries). PARTITION
        PRUNING: files whose path key values fall outside the pushed
        constraint produce no splits at all — zero bytes read for
        excluded partitions (reference: TupleDomain reaching the hive
        split manager)."""
        files, part_types = self._layout(handle)
        # a column may carry SEVERAL domains (planner value set AND a
        # dynamic-filter RangeSet): a file must satisfy all of them
        domains: List[Tuple[str, object]] = [
            (col, vals if isinstance(vals, RangeSet) else set(vals))
            for col, vals in constraint
            if col in part_types
        ]
        splits: List[ConnectorSplit] = []
        for f in files:
            if not all(
                _key_matches(f.keys[col], part_types[col], vals)
                for col, vals in domains
            ):
                continue
            lo = f.row_start
            while lo < f.row_end:
                hi = min(lo + target_split_rows, f.row_end)
                splits.append(ConnectorSplit(handle, lo, hi))
                lo = hi
        if not splits:
            splits.append(ConnectorSplit(handle, 0, 0))
        return SplitSource(splits)

    def create_page_source(
        self, split: ConnectorSplit, columns: Sequence[str]
    ) -> Dict[str, object]:
        import bisect

        files, part_types = self._layout(split.table)
        schema = self._metadata.get_table_schema(split.table)
        out: Dict[str, List] = {name: [] for name in columns}
        # files hold contiguous sorted ranges: bisect to the first
        # overlapping file instead of scanning all of them per split
        starts = [f.row_start for f in files]
        i = max(bisect.bisect_right(starts, split.row_start) - 1, 0)
        for f in files[i:]:
            if f.row_start >= split.row_end:
                break
            lo = max(split.row_start, f.row_start)
            hi = min(split.row_end, f.row_end)
            if lo >= hi:
                continue
            self._append_file_range(
                f, lo - f.row_start, hi - f.row_start, columns,
                schema, part_types, out,
            )
        return {
            name: _concat_payloads(parts, schema[name])
            for name, parts in out.items()
        }

    def _append_file_range(
        self, f, lo, hi, columns, schema, part_types, out
    ):
        pf = self._file(f)
        file_cols = [c for c in columns if c not in part_types]
        table = None
        if file_cols:
            md = pf.metadata
            groups, first_lo, acc = [], 0, 0
            for rg in range(md.num_row_groups):
                n = md.row_group(rg).num_rows
                if acc < hi and acc + n > lo:
                    if not groups:
                        first_lo = acc
                    groups.append(rg)
                acc += n
            table = pf.read_row_groups(groups, columns=file_cols)
            table = table.slice(lo - first_lo, hi - lo)
        for name in columns:
            if name in part_types:
                out[name].append(
                    _const_column(
                        f.keys[name], part_types[name], hi - lo
                    )
                )
            else:
                out[name].append(
                    arrow_column_to_payload(
                        table.column(name), schema[name]
                    )
                )

    # hive partition values come from the PATH: one constant per file


def _key_matches(raw: str, t: T.DataType, allowed) -> bool:
    """Does a path key value satisfy a pushed constraint domain —
    a value set, or a dynamic-filter :class:`RangeSet` (inclusive
    numeric bounds)? BIGINT keys compare numerically — including
    string-carried integer literals (the planner's IN-list coercion
    keeps '2024' as str); anything unparseable keeps the file
    (over-retain, never over-prune: the filter still applies)."""
    if isinstance(allowed, RangeSet):
        if t.name == "bigint":
            try:
                return allowed.lo <= int(raw) <= allowed.hi
            except (TypeError, ValueError):
                return True  # can't interpret: don't prune on it
        # string/date/decimal path keys: no safe numeric ordering of
        # the raw text — over-retain
        return True
    if t.name == "bigint":
        out = False
        for v in allowed:
            try:
                if int(str(v)) == int(raw):
                    return True
            except (TypeError, ValueError):
                return True  # can't interpret: don't prune on it
        return out
    if t.is_string:
        return str(raw) in {str(v) for v in allowed}
    # date/decimal keys: constraint values are engine-internal units
    # (epoch days, unscaled ints) while path values are text — skip
    # enumeration-time pruning (over-retain; the filter still applies)
    return True


def _const_column(value: str, t: T.DataType, n: int):
    if t.is_string:
        return DictColumn(
            ids=np.zeros(n, np.int32),
            values=np.asarray([value], dtype=object),
        )
    if t.name == "date":
        import datetime

        days = (
            datetime.date.fromisoformat(value)
            - datetime.date(1970, 1, 1)
        ).days
        return np.full(n, days, dtype=np.int64)
    if t.is_decimal:
        from decimal import Decimal

        unscaled = int(
            (Decimal(value) * (10 ** t.scale)).to_integral_value()
        )
        return np.full(n, unscaled, dtype=np.int64)
    return np.full(n, int(value), dtype=np.int64)


def _concat_payloads(parts: List, t: T.DataType):
    """Concatenate per-file payload chunks into one column payload
    (dictionary union + id remap lives in the shared staging helper)."""
    from presto_tpu.exec.staging import merge_column_chunks

    return merge_column_chunks(parts, dtype=t)
