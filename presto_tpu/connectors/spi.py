"""Connector SPI: the engine <-> data-source contract.

Reference parity: ``presto-spi`` interfaces — ``ConnectorMetadata`` (table
/schema resolution, statistics), ``ConnectorSplitManager`` (split
enumeration), ``ConnectorPageSourceProvider`` (split -> pages) — SURVEY.md
§2.2. Pushdown surface kept minimal for round 1: column pruning (the
``columns`` argument) and row-range splits; constraint/limit pushdown are
later rounds.

TPU-first note: a page source yields *host* columnar data (numpy) plus
type metadata; the execution layer stages it into device Pages at the
fragment boundary (SURVEY.md §7 step 1 "host-side encode/decode").
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from presto_tpu import types as T


@dataclasses.dataclass(frozen=True)
class TableHandle:
    """Opaque engine-side reference to a connector table.

    ``snapshot`` pins a committed table version for snapshot-capable
    connectors (the streaming-ingest lane, ``server/ingest.py``):
    None = the live/current contents (every pre-snapshot handle).
    The snapshot participates in equality/hash on purpose — staged
    pages of different versions must never share a cache entry — so
    cache *invalidation* matches on :attr:`table_key` instead."""

    catalog: str
    schema: str
    table: str
    snapshot: Optional[int] = None

    @property
    def table_key(self) -> tuple:
        """Version-blind identity: (catalog, schema, table). The match
        key for write-path cache invalidation, which must drop every
        snapshot's entries of a written table."""
        return (self.catalog, self.schema, self.table)


@dataclasses.dataclass(frozen=True)
class ColumnStats:
    """Per-column statistics for the cost-based optimizer (reference:
    ConnectorTableStatistics / StatsCalculator inputs)."""

    distinct_count: Optional[float] = None
    min_value: Optional[float] = None
    max_value: Optional[float] = None
    null_fraction: float = 0.0


@dataclasses.dataclass(frozen=True)
class TableStats:
    row_count: Optional[float] = None
    columns: Dict[str, ColumnStats] = dataclasses.field(default_factory=dict)
    # declared key (possibly composite) with at most one row per value
    primary_key: Optional[tuple] = None


@dataclasses.dataclass(frozen=True)
class RangeSet:
    """TupleDomain-lite RANGE domain for ``get_splits`` constraints:
    the column's allowed values lie in the inclusive ``[lo, hi]``
    interval (native engine representation — unscaled ints for
    decimals, epoch days for dates). Connectors MAY use it to skip
    splits whose min/max statistics fall wholly outside the range
    (parquet row groups, ORC stripes, hive partition keys); ignoring
    it is always correct — the originating filter still applies.
    Produced by the dynamic-filter plane (``exec/dynfilter.py``)."""

    lo: object
    hi: object


@dataclasses.dataclass(frozen=True)
class ConnectorSplit:
    """One unit of scan parallelism (reference: ConnectorSplit).

    Row-range based: [row_start, row_end) of the table's row space.
    ``addresses`` is the locality hint for the scheduler."""

    table: TableHandle
    row_start: int
    row_end: int
    addresses: Sequence[str] = ()

    @property
    def num_rows(self) -> int:
        return self.row_end - self.row_start


def coalesce_kept_chunks(
    handle: TableHandle,
    chunk_rows: Sequence[int],
    keep: Sequence[bool],
    target_split_rows: int,
) -> List[ConnectorSplit]:
    """Build row-range splits from a table's physical chunks (parquet
    row groups, ORC stripes) after constraint pruning: consecutive
    KEPT chunks coalesce into one split, a pruned chunk closes the
    open split (its rows are never covered), and splits close at
    ``target_split_rows``. An all-pruned (or empty) table yields the
    canonical zero-row sentinel split. The ONE coalescing loop both
    file connectors share — its start-sentinel boundary logic is easy
    to get subtly wrong twice."""
    splits: List[ConnectorSplit] = []
    start: Optional[int] = None
    acc = 0
    for n, kept in zip(chunk_rows, keep):
        if not kept:
            if start is not None and acc > start:
                splits.append(ConnectorSplit(handle, start, acc))
            start = None
            acc += n
            continue
        if start is None:
            start = acc
        acc += n
        if acc - start >= target_split_rows:
            splits.append(ConnectorSplit(handle, start, acc))
            start = acc
    if start is not None and (acc > start or not splits):
        splits.append(ConnectorSplit(handle, start, acc))
    if not splits:
        splits.append(ConnectorSplit(handle, 0, 0))
    return splits


class SplitSource:
    """Batched split enumeration (reference: SplitSource.getNextBatch)."""

    def __init__(self, splits: List[ConnectorSplit]):
        self._splits = splits
        self._pos = 0

    def next_batch(self, max_size: int) -> List[ConnectorSplit]:
        batch = self._splits[self._pos : self._pos + max_size]
        self._pos += len(batch)
        return batch

    @property
    def exhausted(self) -> bool:
        return self._pos >= len(self._splits)


class ConnectorMetadata:
    def list_schemas(self) -> List[str]:
        raise NotImplementedError

    def list_tables(self, schema: str) -> List[str]:
        raise NotImplementedError

    def get_table_schema(self, handle: TableHandle) -> Dict[str, T.DataType]:
        raise NotImplementedError

    def get_table_stats(self, handle: TableHandle) -> TableStats:
        return TableStats()


def payload_len(col) -> int:
    """Row count of one SPI column payload (ndarray, DictColumn, or
    MaskedColumn)."""
    if hasattr(col, "ids"):
        return len(col.ids)
    if hasattr(col, "data"):
        return len(col.data)
    return len(col)


class Connector:
    """One mounted catalog (reference: Connector from ConnectorFactory)."""

    def cacheable(self) -> bool:
        """False for live introspection sources (system tables) whose
        staged pages must not be reused across queries."""
        return True

    def coordinator_only(self) -> bool:
        """True when this catalog's data lives only in the coordinator
        process (system.runtime.*): the scheduler must not ship its
        scans to workers, whose copies of the tables are empty."""
        return False

    def prunes_splits(self) -> bool:
        """True when this connector USES scan constraints to skip
        splits (hive partition pruning, parquet row-group / ORC stripe
        stats). Statements over such catalogs bypass the statement-
        level plan cache: a cached parameterized plan blocks constraint
        extraction from equality/IN literals, which would silently cost
        these connectors their pruning. Connectors that ignore
        constraints (the default) keep full plan-cache sharing."""
        return False

    def metadata(self) -> ConnectorMetadata:
        raise NotImplementedError

    def pin_snapshot(self, handle: TableHandle) -> TableHandle:
        """Resolve ``handle`` to a pinned committed version for the
        duration of one plan (Iceberg-style snapshot reads): the
        planner calls this once per table scan, so every split, staged
        page, and capacity retry of the plan reads ONE immutable
        version — long scans are isolated from concurrent ingest
        commits. The default (and any connector without versioned
        tables) returns the handle unchanged: reads keep the live
        contents, bit-exact pre-snapshot behavior."""
        return handle

    def get_splits(
        self,
        handle: TableHandle,
        target_split_rows: int = 1 << 20,
        constraint: Sequence = (),
    ) -> SplitSource:
        """Enumerate splits. ``constraint`` is TupleDomain-lite advice
        from the planner — (column, allowed-values) pairs a connector
        MAY use to skip splits (hive partition pruning); the engine
        still applies the originating filter, so ignoring it is always
        correct (and the default implementations do)."""
        raise NotImplementedError

    def create_page_source(
        self, split: ConnectorSplit, columns: Sequence[str]
    ) -> Dict[str, np.ndarray]:
        """Produce host columnar data for a split, pruned to ``columns``.

        Returns {column -> numpy array}; None entries in object arrays
        mark SQL NULLs. (Reference: ConnectorPageSource.getNextPage.)"""
        raise NotImplementedError

    # -- write path (optional; reference: ConnectorPageSink) --------------

    def supports_writes(self) -> bool:
        return False

    def create_table(self, handle: TableHandle, schema: Dict[str, T.DataType]):
        raise NotImplementedError(f"{type(self).__name__} is read-only")

    def append_rows(self, handle: TableHandle, data: Dict[str, np.ndarray]):
        raise NotImplementedError(f"{type(self).__name__} is read-only")
