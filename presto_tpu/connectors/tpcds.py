"""TPC-DS connector: deterministic star-schema data generated on the fly.

Reference parity: ``presto-tpcds`` — like ``presto-tpch``, data derived
from the scale factor at scan time with zero stored bytes (SURVEY.md
§2.2), so the TPC-DS benchmark configs of BASELINE.json (Q64/Q95) run
against exactly reproducible fixtures and the sqlite oracle can assert
exact results over the SAME data.

TPU-first: reuses the closed-form generator machinery of
``connectors.tpch`` — splitmix64 streams keyed by (column, row index),
arithmetic bijections for multi-line orders (ticket/order cycles), and
dictionary-id varchar columns (strings never materialize per row).
Returns tables are derived row-maps of their sales tables (return j
references sale row j*K), which keeps the (item, order) FK pairs exact
in O(1) per row — the property official dsdgen gets from sequential
generation.

Coverage: the 17 tables Q64/Q95 touch (store/catalog/web sales +
returns, date_dim, item, customer, customer_address,
customer_demographics, household_demographics, income_band, store,
promotion, warehouse, web_site), with the columns those queries and the
general test corpus exercise. Distributions are TPC-DS-shaped, not
bit-identical to dsdgen (BASELINE.md provenance: no published reference
numbers exist; correctness is oracle-diffed).
"""

from __future__ import annotations

import datetime
from typing import Dict, Sequence

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.spi import (
    ColumnStats,
    Connector,
    ConnectorMetadata,
    ConnectorSplit,
    SplitSource,
    TableHandle,
    TableStats,
)
from presto_tpu.connectors.tpch import (
    COLORS,
    DictColumn,
    _fixed,
    _LazyCombo,
    _numbered,
    _stream,
    _uniform,
)

SCHEMAS = {"tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0}

_EPOCH = datetime.date(1970, 1, 1)
_D_START = (datetime.date(1990, 1, 1) - _EPOCH).days
_D_END = (datetime.date(2002, 12, 31) - _EPOCH).days
N_DATES = _D_END - _D_START + 1  # 4748 days
_DATE_SK0 = 2415022  # official dsdgen julian-ish base for d_date_sk

#: sales dates concentrated where the benchmark queries look (Q64 self-
#: joins syear 1999 x 2000; Q95 windows inside 1999) — official dsdgen
#: also clusters sales in the 1998-2002 band
_SOLD_LO = (datetime.date(1998, 1, 1) - _EPOCH).days
_SOLD_HI = (datetime.date(2000, 12, 31) - _EPOCH).days

REASON_DESCS = [
    "Did not fit", "Did not like the color", "Did not like the model",
    "Did not like the warranty", "Does not work", "Duplicate purchase",
    "Found a better extension", "Found a better price", "Gift exchange",
    "Lost my job", "No service location in my area", "Not the product",
    "Package was damaged", "Parts missing", "Stopped working",
    "Unauthorized purchase", "Wrong size",
]
SHIP_TYPES = ["EXPRESS", "LIBRARY", "NEXT DAY", "OVERNIGHT", "REGULAR",
              "TWO DAY"]
SHIP_CODES = ["AIR", "SEA", "SURFACE"]
CARRIERS = ["AIRBORNE", "ALLIANCE", "BARIAN", "BOXBUNDLES", "DHL",
            "FEDEX", "GERMA", "GREAT EASTERN", "HARMSTORF", "LATVIAN",
            "MSC", "ORIENTAL", "PRIVATECARRIER", "RUPEKSA", "TBS", "UPS",
            "USPS", "ZHOU", "ZOUROS", "DIAMOND"]
CC_NAMES = ["California", "Hawaii/Alaska", "Mid Atlantic", "Midwest",
            "NY Metro", "North Midwest", "Northwest", "Pacific Northwest",
            "South Atlantic", "Southwest"]
MARITAL = ["D", "M", "S", "U", "W"]
GENDER = ["F", "M"]
EDUCATION = [
    "2 yr Degree", "4 yr Degree", "Advanced Degree", "College",
    "Primary", "Secondary", "Unknown",
]
CREDIT = ["Good", "High Risk", "Low Risk", "Unknown"]
BUY_POTENTIAL = ["0-500", "1001-5000", "501-1000", ">10000", "5001-10000",
                 "Unknown"]
STATES = ["CA", "GA", "IL", "MI", "NY", "OH", "PA", "TN", "TX", "WA"]
CITIES = [
    "Antioch", "Bridgeport", "Centerville", "Clifton", "Fairview",
    "Five Points", "Glendale", "Greenfield", "Liberty", "Lincoln",
    "Marion", "Midway", "Mount Olive", "Mount Zion", "Oak Grove",
    "Oak Hill", "Oakland", "Pleasant Grove", "Pleasant Hill", "Riverside",
    "Salem", "Shady Grove", "Springdale", "Spring Hill", "Sulphur Springs",
    "Union", "Unionville", "Walnut Grove", "White Oak", "Woodville",
]
STREET_W1 = [
    "1st", "2nd", "3rd", "4th", "5th", "6th", "7th", "8th", "9th", "10th",
    "Adams", "Birch", "Cedar", "Chestnut", "Church", "College", "Davis",
    "Dogwood", "East", "Elm",
]
STREET_W2 = [
    "Ave", "Blvd", "Circle", "Court", "Dr", "Lane", "Parkway", "Pkwy",
    "RD", "ST", "Street", "Way", "Wy", "Boulevard", "Cir", "Ct", "Drive",
    "Ln", "Pl", "Road",
]
STORE_NAMES = ["able", "anti", "ation", "bar", "cally", "eing", "ese",
               "n st", "ought", "pri"]
COUNTIES = [
    "Barrow County", "Bronx County", "Daviess County", "Fairfield County",
    "Franklin Parish", "Luce County", "Mobile County", "Richland County",
    "Walker County", "Williamson County",
]
COMPANIES = ["pri", "able", "ese", "anti", "cally", "ation"]
CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women"]
CLASSES = [
    "accent", "arts", "athletic", "bedding", "bridal", "blinds/shades",
    "bracelets", "classical", "computers", "consignment", "country",
    "curtains/drapes", "decor", "dresses", "fiction", "history",
]
FIRST_NAMES = [
    "Aaron", "Alice", "Amy", "Anna", "Brian", "Carol", "Chad", "Daniel",
    "David", "Diane", "Earl", "Edna", "Frank", "Grace", "Helen", "Irene",
    "Jack", "James", "Karen", "Larry", "Linda", "Maria", "Nancy", "Oscar",
    "Paul", "Rachel", "Sarah", "Thomas", "Velma", "Walter",
]
LAST_NAMES = [
    "Adams", "Baker", "Brown", "Clark", "Davis", "Evans", "Garcia",
    "Harris", "Hill", "Johnson", "Jones", "King", "Lewis", "Lopez",
    "Martin", "Miller", "Moore", "Nelson", "Parker", "Roberts",
    "Robinson", "Scott", "Smith", "Taylor", "Thompson", "Turner",
    "Walker", "White", "Williams", "Young",
]
PROMO_CHANNELS = ["N", "Y"]

_STREET_NAME = _LazyCombo(STREET_W1, STREET_W2)
_I_NAME = _LazyCombo(COLORS, COLORS)
_ZIPS = [f"{z:05d}" for z in range(10000, 10000 + 100 * 97, 97)]
_STREET_NUMS = [str(n) for n in range(1, 1001)]

D7_2 = T.decimal(7, 2)


# -------------------------------------------------- multi-line order maps

#: tickets/orders carry 1..4 line items cycling; closed form mirrors
#: tpch's lineitem cycle (connectors.tpch._lineitem_order)
_CYC = np.array([0, 1, 3, 6, 10], dtype=np.int64)  # prefix sums of 1..4
_ROWS_PER_CYC = 10
_ORDERS_PER_CYC = 4


def _sparse_amount(pick_tag: int, amt_tag: int, rows: np.ndarray):
    """80%-zero coupon amounts, 1.00..20.00 otherwise (shared by the
    store and catalog channels so the sparsity stays aligned)."""
    r = _uniform(pick_tag, rows, 0, 9)
    return np.where(r < 8, 0, _uniform(amt_tag, rows, 100, 2000))


def _order_of_row(rows: np.ndarray):
    """sales row -> (order index 0-based, line number 1-based)."""
    cyc, rr = np.divmod(rows, _ROWS_PER_CYC)
    j = np.searchsorted(_CYC, rr, side="right") - 1
    return cyc * _ORDERS_PER_CYC + j, rr - _CYC[j] + 1


# ------------------------------------------------------------- row counts


def _counts(sf: float) -> Dict[str, int]:
    root = max(sf, 0.01) ** 0.5
    ss = max(int(2_880_000 * sf), 100)
    cs = max(int(1_440_000 * sf), 100)
    ws = max(int(720_000 * sf), 90)
    return {
        "date_dim": N_DATES,
        "time_dim": 86_400,
        "reason": max(int(35 * root), 5),
        "ship_mode": 20,
        "call_center": max(int(6 * root), 2),
        "web_page": max(int(60 * sf), 10),
        "catalog_page": max(int(11_718 * root), 100),
        # items x warehouses x weekly inventory dates (official dsdgen
        # shape: one snapshot per week across the 1998-2002 band)
        "inventory": max(int(18_000 * sf), 100) * 5 * 261,
        "income_band": 20,
        "customer_demographics": 5600,  # 2*5*7*20*4 mixed radix
        "household_demographics": 1200,  # 20*6*10 mixed radix
        "warehouse": 5,
        "web_site": 6,
        "store": max(int(12 * root), 2),
        "promotion": max(int(300 * sf), 3),
        "item": max(int(18_000 * sf), 100),
        "customer": max(int(100_000 * sf), 500),
        "customer_address": max(int(50_000 * sf), 250),
        "store_sales": ss,
        "store_returns": ss // 2,
        "catalog_sales": cs,
        "catalog_returns": cs // 2,
        "web_sales": ws,
        "web_returns": ws // 3,
    }


# --------------------------------------------------------------- schemas

TABLE_SCHEMAS: Dict[str, Dict[str, T.DataType]] = {
    "date_dim": {
        "d_date_sk": T.INTEGER,
        "d_date": T.DATE,
        "d_year": T.INTEGER,
        "d_moy": T.INTEGER,
        "d_dom": T.INTEGER,
        "d_dow": T.INTEGER,
        "d_qoy": T.INTEGER,
        "d_day_name": T.VARCHAR,
        "d_month_seq": T.INTEGER,
        "d_quarter_name": T.VARCHAR,
        "d_week_seq": T.INTEGER,
    },
    "income_band": {
        "ib_income_band_sk": T.INTEGER,
        "ib_lower_bound": T.INTEGER,
        "ib_upper_bound": T.INTEGER,
    },
    "time_dim": {
        "t_time_sk": T.INTEGER,
        "t_time_id": T.VARCHAR,
        "t_time": T.INTEGER,
        "t_hour": T.INTEGER,
        "t_minute": T.INTEGER,
        "t_second": T.INTEGER,
        "t_am_pm": T.VARCHAR,
        "t_meal_time": T.VARCHAR,
        "t_shift": T.VARCHAR,
    },
    "reason": {
        "r_reason_sk": T.INTEGER,
        "r_reason_id": T.VARCHAR,
        "r_reason_desc": T.VARCHAR,
    },
    "ship_mode": {
        "sm_ship_mode_sk": T.INTEGER,
        "sm_ship_mode_id": T.VARCHAR,
        "sm_type": T.VARCHAR,
        "sm_code": T.VARCHAR,
        "sm_carrier": T.VARCHAR,
    },
    "call_center": {
        "cc_call_center_sk": T.INTEGER,
        "cc_call_center_id": T.VARCHAR,
        "cc_name": T.VARCHAR,
        "cc_manager": T.VARCHAR,
        "cc_county": T.VARCHAR,
        "cc_state": T.VARCHAR,
    },
    "web_page": {
        "wp_web_page_sk": T.INTEGER,
        "wp_web_page_id": T.VARCHAR,
        "wp_url": T.VARCHAR,
        "wp_char_count": T.INTEGER,
        "wp_link_count": T.INTEGER,
    },
    "catalog_page": {
        "cp_catalog_page_sk": T.INTEGER,
        "cp_catalog_page_id": T.VARCHAR,
        "cp_catalog_number": T.INTEGER,
        "cp_catalog_page_number": T.INTEGER,
        "cp_department": T.VARCHAR,
    },
    "inventory": {
        "inv_date_sk": T.INTEGER,
        "inv_item_sk": T.INTEGER,
        "inv_warehouse_sk": T.INTEGER,
        "inv_quantity_on_hand": T.INTEGER,
    },
    "customer_demographics": {
        "cd_demo_sk": T.INTEGER,
        "cd_gender": T.VARCHAR,
        "cd_marital_status": T.VARCHAR,
        "cd_education_status": T.VARCHAR,
        "cd_purchase_estimate": T.INTEGER,
        "cd_credit_rating": T.VARCHAR,
        "cd_dep_count": T.INTEGER,
        "cd_dep_employed_count": T.INTEGER,
        "cd_dep_college_count": T.INTEGER,
    },
    "household_demographics": {
        "hd_demo_sk": T.INTEGER,
        "hd_income_band_sk": T.INTEGER,
        "hd_buy_potential": T.VARCHAR,
        "hd_dep_count": T.INTEGER,
        "hd_vehicle_count": T.INTEGER,
    },
    "warehouse": {
        "w_warehouse_sk": T.INTEGER,
        "w_warehouse_name": T.VARCHAR,
        "w_state": T.VARCHAR,
        "w_warehouse_sq_ft": T.INTEGER,
        "w_city": T.VARCHAR,
        "w_county": T.VARCHAR,
        "w_country": T.VARCHAR,
    },
    "web_site": {
        "web_site_sk": T.INTEGER,
        "web_site_id": T.VARCHAR,
        "web_name": T.VARCHAR,
        "web_company_name": T.VARCHAR,
    },
    "store": {
        "s_store_sk": T.INTEGER,
        "s_store_id": T.VARCHAR,
        "s_store_name": T.VARCHAR,
        "s_city": T.VARCHAR,
        "s_state": T.VARCHAR,
        "s_zip": T.VARCHAR,
        "s_number_employees": T.INTEGER,
        "s_company_name": T.VARCHAR,
        "s_county": T.VARCHAR,
        "s_gmt_offset": T.INTEGER,
        "s_company_id": T.INTEGER,
        "s_street_number": T.VARCHAR,
        "s_street_name": T.VARCHAR,
        "s_street_type": T.VARCHAR,
        "s_suite_number": T.VARCHAR,
        "s_market_id": T.INTEGER,
    },
    "promotion": {
        "p_promo_sk": T.INTEGER,
        "p_promo_id": T.VARCHAR,
        "p_channel_email": T.VARCHAR,
        "p_channel_event": T.VARCHAR,
        "p_channel_dmail": T.VARCHAR,
        "p_channel_tv": T.VARCHAR,
    },
    "item": {
        "i_item_sk": T.INTEGER,
        "i_item_id": T.VARCHAR,
        "i_item_desc": T.VARCHAR,
        "i_product_name": T.VARCHAR,
        "i_color": T.VARCHAR,
        "i_current_price": D7_2,
        "i_category": T.VARCHAR,
        "i_category_id": T.INTEGER,
        "i_class": T.VARCHAR,
        "i_class_id": T.INTEGER,
        "i_brand": T.VARCHAR,
        "i_brand_id": T.INTEGER,
        "i_manufact_id": T.INTEGER,
        "i_manufact": T.VARCHAR,
        "i_size": T.VARCHAR,
        "i_units": T.VARCHAR,
        "i_manager_id": T.INTEGER,
        "i_wholesale_cost": D7_2,
    },
    "customer": {
        "c_customer_sk": T.INTEGER,
        "c_customer_id": T.VARCHAR,
        "c_first_name": T.VARCHAR,
        "c_last_name": T.VARCHAR,
        "c_current_cdemo_sk": T.INTEGER,
        "c_current_hdemo_sk": T.INTEGER,
        "c_current_addr_sk": T.INTEGER,
        "c_first_sales_date_sk": T.INTEGER,
        "c_first_shipto_date_sk": T.INTEGER,
        "c_birth_year": T.INTEGER,
        "c_birth_month": T.INTEGER,
        "c_birth_day": T.INTEGER,
        "c_birth_country": T.VARCHAR,
        "c_login": T.VARCHAR,
        "c_email_address": T.VARCHAR,
        "c_last_review_date_sk": T.INTEGER,
        "c_salutation": T.VARCHAR,
        "c_preferred_cust_flag": T.VARCHAR,
    },
    "customer_address": {
        "ca_address_sk": T.INTEGER,
        "ca_street_number": T.VARCHAR,
        "ca_street_name": T.VARCHAR,
        "ca_city": T.VARCHAR,
        "ca_state": T.VARCHAR,
        "ca_zip": T.VARCHAR,
        "ca_county": T.VARCHAR,
        "ca_gmt_offset": T.INTEGER,
        "ca_country": T.VARCHAR,
        "ca_street_type": T.VARCHAR,
        "ca_suite_number": T.VARCHAR,
        "ca_location_type": T.VARCHAR,
    },
    "store_sales": {
        "ss_sold_date_sk": T.INTEGER,
        "ss_item_sk": T.INTEGER,
        "ss_customer_sk": T.INTEGER,
        "ss_cdemo_sk": T.INTEGER,
        "ss_hdemo_sk": T.INTEGER,
        "ss_addr_sk": T.INTEGER,
        "ss_store_sk": T.INTEGER,
        "ss_promo_sk": T.INTEGER,
        "ss_ticket_number": T.INTEGER,
        "ss_quantity": T.INTEGER,
        "ss_wholesale_cost": D7_2,
        "ss_list_price": D7_2,
        "ss_sales_price": D7_2,
        "ss_ext_sales_price": D7_2,
        "ss_ext_list_price": D7_2,
        "ss_ext_tax": D7_2,
        "ss_coupon_amt": D7_2,
        "ss_net_profit": D7_2,
        "ss_ext_discount_amt": D7_2,
        "ss_ext_wholesale_cost": D7_2,
        "ss_net_paid": D7_2,
        "ss_sold_time_sk": T.INTEGER,
    },
    "store_returns": {
        "sr_returned_date_sk": T.INTEGER,
        "sr_item_sk": T.INTEGER,
        "sr_ticket_number": T.INTEGER,
        "sr_return_amt": D7_2,
        "sr_net_loss": D7_2,
        "sr_store_sk": T.INTEGER,
        "sr_customer_sk": T.INTEGER,
        "sr_return_quantity": T.INTEGER,
        "sr_reason_sk": T.INTEGER,
        "sr_cdemo_sk": T.INTEGER,
    },
    "catalog_sales": {
        "cs_sold_date_sk": T.INTEGER,
        "cs_ship_date_sk": T.INTEGER,
        "cs_bill_customer_sk": T.INTEGER,
        "cs_bill_cdemo_sk": T.INTEGER,
        "cs_item_sk": T.INTEGER,
        "cs_promo_sk": T.INTEGER,
        "cs_ship_mode_sk": T.INTEGER,
        "cs_call_center_sk": T.INTEGER,
        "cs_warehouse_sk": T.INTEGER,
        "cs_order_number": T.INTEGER,
        "cs_quantity": T.INTEGER,
        "cs_list_price": D7_2,
        "cs_sales_price": D7_2,
        "cs_coupon_amt": D7_2,
        "cs_ext_list_price": D7_2,
        "cs_ext_sales_price": D7_2,
        "cs_net_profit": D7_2,
        "cs_catalog_page_sk": T.INTEGER,
        "cs_bill_hdemo_sk": T.INTEGER,
        "cs_ext_discount_amt": D7_2,
        "cs_wholesale_cost": D7_2,
        "cs_ext_ship_cost": D7_2,
        "cs_ext_wholesale_cost": D7_2,
        "cs_net_paid": D7_2,
        "cs_ship_addr_sk": T.INTEGER,
        "cs_bill_addr_sk": T.INTEGER,
        "cs_ship_customer_sk": T.INTEGER,
        "cs_sold_time_sk": T.INTEGER,
    },
    "catalog_returns": {
        "cr_returned_date_sk": T.INTEGER,
        "cr_item_sk": T.INTEGER,
        "cr_order_number": T.INTEGER,
        "cr_refunded_cash": D7_2,
        "cr_reversed_charge": D7_2,
        "cr_store_credit": D7_2,
        "cr_return_amount": D7_2,
        "cr_net_loss": D7_2,
        "cr_catalog_page_sk": T.INTEGER,
        "cr_return_quantity": T.INTEGER,
        "cr_returning_customer_sk": T.INTEGER,
        "cr_returning_addr_sk": T.INTEGER,
        "cr_call_center_sk": T.INTEGER,
    },
    "web_sales": {
        "ws_sold_date_sk": T.INTEGER,
        "ws_ship_date_sk": T.INTEGER,
        "ws_item_sk": T.INTEGER,
        "ws_ship_addr_sk": T.INTEGER,
        "ws_web_site_sk": T.INTEGER,
        "ws_warehouse_sk": T.INTEGER,
        "ws_ship_mode_sk": T.INTEGER,
        "ws_order_number": T.INTEGER,
        "ws_ext_ship_cost": D7_2,
        "ws_ext_sales_price": D7_2,
        "ws_net_profit": D7_2,
        "ws_web_page_sk": T.INTEGER,
        "ws_promo_sk": T.INTEGER,
        "ws_sales_price": D7_2,
        "ws_quantity": T.INTEGER,
        "ws_list_price": D7_2,
        "ws_wholesale_cost": D7_2,
        "ws_ext_discount_amt": D7_2,
        "ws_ext_wholesale_cost": D7_2,
        "ws_ext_list_price": D7_2,
        "ws_net_paid": D7_2,
        "ws_sold_time_sk": T.INTEGER,
        "ws_ship_hdemo_sk": T.INTEGER,
        "ws_bill_customer_sk": T.INTEGER,
        "ws_bill_addr_sk": T.INTEGER,
    },
    "web_returns": {
        "wr_returned_date_sk": T.INTEGER,
        "wr_item_sk": T.INTEGER,
        "wr_order_number": T.INTEGER,
        "wr_return_amt": D7_2,
        "wr_net_loss": D7_2,
        "wr_web_page_sk": T.INTEGER,
        "wr_return_quantity": T.INTEGER,
        "wr_returning_customer_sk": T.INTEGER,
        "wr_returning_addr_sk": T.INTEGER,
        "wr_refunded_cash": D7_2,
        "wr_reason_sk": T.INTEGER,
        "wr_refunded_cdemo_sk": T.INTEGER,
        "wr_refunded_addr_sk": T.INTEGER,
        "wr_returning_cdemo_sk": T.INTEGER,
        "wr_fee": D7_2,
    },
}


# ------------------------------------------------------------ generators


class TpcdsGenerator:
    def __init__(self, sf: float):
        self.sf = sf
        self.counts = _counts(sf)

    def generate(
        self, table: str, lo: int, hi: int, columns: Sequence[str]
    ) -> Dict[str, object]:
        rows = np.arange(lo, hi, dtype=np.int64)
        return getattr(self, f"_gen_{table}")(rows, list(columns))

    # -- dimensions ---------------------------------------------------

    def _gen_date_dim(self, rows, columns):
        days = _D_START + rows
        dates = [_EPOCH + datetime.timedelta(days=int(d)) for d in days]
        out = {}
        for c in columns:
            if c == "d_date_sk":
                out[c] = _DATE_SK0 + rows
            elif c == "d_date":
                out[c] = days
            elif c == "d_year":
                out[c] = np.asarray([d.year for d in dates], np.int64)
            elif c == "d_moy":
                out[c] = np.asarray([d.month for d in dates], np.int64)
            elif c == "d_dom":
                out[c] = np.asarray([d.day for d in dates], np.int64)
            elif c == "d_dow":
                out[c] = (days + 4) % 7  # 0=Sunday, matching d_day_name
            elif c == "d_qoy":
                out[c] = np.asarray(
                    [(d.month - 1) // 3 + 1 for d in dates], np.int64
                )
            elif c == "d_quarter_name":
                out[c] = _fixed(
                    [f"{y}Q{q}" for y in range(1990, 2004)
                     for q in range(1, 5)],
                    np.asarray(
                        [(d.year - 1990) * 4 + (d.month - 1) // 3
                         for d in dates],
                        np.int64,
                    ),
                )
            elif c == "d_day_name":
                out[c] = _fixed(
                    ["Sunday", "Monday", "Tuesday", "Wednesday",
                     "Thursday", "Friday", "Saturday"],
                    (days + 4) % 7,  # 1970-01-01 was a Thursday
                )
            elif c == "d_month_seq":
                # monotone month counter (the official dimension's
                # sequence anchor differs; queries only ever use
                # RANGES of it, which are translation-invariant)
                out[c] = np.asarray(
                    [(d.year - 1900) * 12 + d.month - 1 for d in dates],
                    np.int64,
                )
            elif c == "d_week_seq":
                # monotone week counter, Sunday-aligned like d_dow
                out[c] = (days + 4) // 7
        return out

    def _date_sk_for(self, days: np.ndarray) -> np.ndarray:
        """epoch-days -> d_date_sk (clipped into the dimension)."""
        return _DATE_SK0 + np.clip(days - _D_START, 0, N_DATES - 1)

    def _gen_time_dim(self, rows, columns):
        out = {}
        hour = rows // 3600
        for c in columns:
            if c == "t_time_sk":
                out[c] = rows
            elif c == "t_time_id":
                out[c] = _numbered(
                    "Time", self.counts["time_dim"], rows + 1
                )
            elif c == "t_time":
                out[c] = rows
            elif c == "t_hour":
                out[c] = hour
            elif c == "t_minute":
                out[c] = (rows // 60) % 60
            elif c == "t_second":
                out[c] = rows % 60
            elif c == "t_am_pm":
                out[c] = _fixed(["AM", "PM"], (hour >= 12).astype(np.int64))
            elif c == "t_meal_time":
                # official domains: breakfast 6-9, lunch 11-14,
                # dinner 17-20, empty otherwise
                pick = np.where(
                    (hour >= 6) & (hour < 9), 1,
                    np.where(
                        (hour >= 11) & (hour < 14), 2,
                        np.where((hour >= 17) & (hour < 20), 3, 0),
                    ),
                )
                out[c] = _fixed(
                    ["", "breakfast", "lunch", "dinner"], pick
                )
            elif c == "t_shift":
                out[c] = _fixed(
                    ["first", "second", "third"],
                    np.clip(hour // 8, 0, 2),
                )
        return out

    def _gen_reason(self, rows, columns):
        out = {}
        for c in columns:
            if c == "r_reason_sk":
                out[c] = rows + 1
            elif c == "r_reason_id":
                out[c] = _numbered(
                    "Reason", self.counts["reason"], rows + 1
                )
            elif c == "r_reason_desc":
                out[c] = _fixed(REASON_DESCS, rows % len(REASON_DESCS))
        return out

    def _gen_ship_mode(self, rows, columns):
        out = {}
        for c in columns:
            if c == "sm_ship_mode_sk":
                out[c] = rows + 1
            elif c == "sm_ship_mode_id":
                out[c] = _numbered(
                    "ShipMode", self.counts["ship_mode"], rows + 1
                )
            elif c == "sm_type":
                out[c] = _fixed(SHIP_TYPES, rows % len(SHIP_TYPES))
            elif c == "sm_code":
                out[c] = _fixed(SHIP_CODES, (rows // 5) % len(SHIP_CODES))
            elif c == "sm_carrier":
                out[c] = _fixed(CARRIERS, rows % len(CARRIERS))
        return out

    def _gen_call_center(self, rows, columns):
        out = {}
        for c in columns:
            if c == "cc_call_center_sk":
                out[c] = rows + 1
            elif c == "cc_call_center_id":
                out[c] = _numbered(
                    "CallCenter", self.counts["call_center"], rows + 1
                )
            elif c == "cc_name":
                out[c] = _fixed(CC_NAMES, rows % len(CC_NAMES))
            elif c == "cc_manager":
                out[c] = _numbered(
                    "Manager", self.counts["call_center"], rows + 1
                )
            elif c == "cc_county":
                out[c] = _fixed(COUNTIES, rows % len(COUNTIES))
            elif c == "cc_state":
                out[c] = _fixed(STATES, rows % len(STATES))
        return out

    def _gen_web_page(self, rows, columns):
        out = {}
        for c in columns:
            if c == "wp_web_page_sk":
                out[c] = rows + 1
            elif c == "wp_web_page_id":
                out[c] = _numbered(
                    "WebPage", self.counts["web_page"], rows + 1
                )
            elif c == "wp_url":
                out[c] = _fixed(["http://www.foo.com"], rows * 0)
            elif c == "wp_char_count":
                out[c] = _uniform(3101, rows, 100, 8000)
            elif c == "wp_link_count":
                out[c] = _uniform(3102, rows, 2, 25)
        return out

    def _gen_catalog_page(self, rows, columns):
        out = {}
        for c in columns:
            if c == "cp_catalog_page_sk":
                out[c] = rows + 1
            elif c == "cp_catalog_page_id":
                out[c] = _numbered(
                    "CatalogPage", self.counts["catalog_page"], rows + 1
                )
            elif c == "cp_catalog_number":
                out[c] = rows // 108 + 1  # 108 pages per catalog
            elif c == "cp_catalog_page_number":
                out[c] = rows % 108 + 1
            elif c == "cp_department":
                out[c] = _fixed(["DEPARTMENT"], rows * 0)
        return out

    def _gen_inventory(self, rows, columns):
        # row = ((week * n_items) + item) * 5 + warehouse: every
        # (item, warehouse) pair snapshots once per week
        n_items = self.counts["item"]
        wh = rows % 5
        item = (rows // 5) % n_items
        week = rows // (5 * n_items)
        out = {}
        for c in columns:
            if c == "inv_date_sk":
                out[c] = self._date_sk_for(_SOLD_LO + week * 7)
            elif c == "inv_item_sk":
                out[c] = item + 1
            elif c == "inv_warehouse_sk":
                out[c] = wh + 1
            elif c == "inv_quantity_on_hand":
                out[c] = _uniform(3201, rows, 0, 1000)
        return out

    def _gen_income_band(self, rows, columns):
        out = {}
        for c in columns:
            if c == "ib_income_band_sk":
                out[c] = rows + 1
            elif c == "ib_lower_bound":
                out[c] = rows * 10000
            elif c == "ib_upper_bound":
                out[c] = rows * 10000 + 9999
        return out

    def _gen_customer_demographics(self, rows, columns):
        out = {}
        for c in columns:
            if c == "cd_demo_sk":
                out[c] = rows + 1
            elif c == "cd_gender":
                out[c] = _fixed(GENDER, rows % 2)
            elif c == "cd_marital_status":
                out[c] = _fixed(MARITAL, (rows // 2) % 5)
            elif c == "cd_education_status":
                out[c] = _fixed(EDUCATION, (rows // 10) % 7)
            elif c == "cd_purchase_estimate":
                out[c] = 500 * (1 + (rows // 70) % 20)
            elif c == "cd_credit_rating":
                out[c] = _fixed(CREDIT, (rows // 1400) % 4)
            elif c == "cd_dep_count":
                out[c] = (rows // 35) % 7
            elif c == "cd_dep_employed_count":
                out[c] = (rows // 245) % 7
            elif c == "cd_dep_college_count":
                out[c] = (rows // 1715) % 7
        return out

    def _gen_household_demographics(self, rows, columns):
        out = {}
        for c in columns:
            if c == "hd_demo_sk":
                out[c] = rows + 1
            elif c == "hd_income_band_sk":
                out[c] = rows % 20 + 1
            elif c == "hd_buy_potential":
                out[c] = _fixed(BUY_POTENTIAL, (rows // 20) % 6)
            elif c == "hd_dep_count":
                out[c] = (rows // 120) % 10
            elif c == "hd_vehicle_count":
                out[c] = (rows // 7) % 6 - 1  # official domain -1..4
        return out

    def _gen_warehouse(self, rows, columns):
        out = {}
        for c in columns:
            if c == "w_warehouse_sk":
                out[c] = rows + 1
            elif c == "w_warehouse_sq_ft":
                out[c] = _uniform(1220, rows, 50000, 1000000)
            elif c == "w_city":
                out[c] = _fixed(CITIES, rows % len(CITIES))
            elif c == "w_county":
                out[c] = _fixed(COUNTIES, rows % len(COUNTIES))
            elif c == "w_country":
                out[c] = _fixed(["United States"], rows * 0)
            elif c == "w_warehouse_name":
                out[c] = _fixed(
                    ["Bad cards must make.",
                     "Conventional childr",
                     "Doors canno",
                     "Important issues liv",
                     "Rooms cook ",
                     ][: max(int(self.counts["warehouse"]), 1)],
                    rows % self.counts["warehouse"],
                )
            elif c == "w_state":
                out[c] = _fixed(STATES, rows % len(STATES))
        return out

    def _gen_web_site(self, rows, columns):
        out = {}
        for c in columns:
            if c == "web_site_sk":
                out[c] = rows + 1
            elif c == "web_site_id":
                out[c] = _numbered("site", self.counts["web_site"], rows + 1)
            elif c == "web_name":
                out[c] = _numbered("web", self.counts["web_site"], rows + 1)
            elif c == "web_company_name":
                # 2 of 6 sites belong to 'pri' (Q95's company filter must
                # select a meaningful slice at every scale)
                out[c] = _fixed(COMPANIES, rows % 3)
        return out

    def _gen_store(self, rows, columns):
        out = {}
        for c in columns:
            if c == "s_store_sk":
                out[c] = rows + 1
            elif c == "s_store_id":
                out[c] = _numbered("Store", self.counts["store"], rows + 1)
            elif c == "s_store_name":
                out[c] = _fixed(STORE_NAMES, rows % len(STORE_NAMES))
            elif c == "s_city":
                out[c] = _fixed(CITIES, rows % len(CITIES))
            elif c == "s_number_employees":
                out[c] = _uniform(1210, rows, 200, 300)
            elif c == "s_state":
                out[c] = _fixed(STATES, rows % len(STATES))
            elif c == "s_zip":
                out[c] = _fixed(_ZIPS, rows % len(_ZIPS))
            elif c == "s_company_name":
                out[c] = _fixed(["Unknown", "ought"], rows % 2)
            elif c == "s_county":
                out[c] = _fixed(COUNTIES, rows % len(COUNTIES))
            elif c == "s_gmt_offset":
                # continental offsets; -5 modal like customer_address
                out[c] = -5 - (rows % 4) % 3 - (rows % 4) // 3 * 3
            elif c == "s_company_id":
                out[c] = rows % 2 + 1
            elif c == "s_street_number":
                out[c] = _fixed(
                    _STREET_NUMS,
                    _uniform(1211, rows, 0, len(_STREET_NUMS) - 1),
                )
            elif c == "s_street_name":
                out[c] = _STREET_NAME.column(1212, rows)
            elif c == "s_street_type":
                out[c] = _fixed(
                    ["Street", "Ave", "Blvd", "Road", "Lane"],
                    rows % 5,
                )
            elif c == "s_suite_number":
                out[c] = _fixed(
                    [f"Suite {n}" for n in range(0, 300, 10)],
                    _uniform(1213, rows, 0, 29),
                )
            elif c == "s_market_id":
                out[c] = rows % 10 + 1
        return out

    def _gen_promotion(self, rows, columns):
        out = {}
        for c in columns:
            if c == "p_promo_sk":
                out[c] = rows + 1
            elif c == "p_promo_id":
                out[c] = _numbered(
                    "Promo", self.counts["promotion"], rows + 1
                )
            elif c == "p_channel_email":
                out[c] = _fixed(PROMO_CHANNELS, rows % 2)
            elif c == "p_channel_event":
                # phase-shifted vs email so OR filters select a real mix
                out[c] = _fixed(PROMO_CHANNELS, (rows // 2) % 2)
            elif c == "p_channel_dmail":
                out[c] = _fixed(PROMO_CHANNELS, (rows // 4) % 2)
            elif c == "p_channel_tv":
                out[c] = _fixed(PROMO_CHANNELS, (rows // 8) % 2)
        return out

    def _gen_item(self, rows, columns):
        # hoisted picks: each id/name pair (category, class, brand,
        # manufact) is functionally dependent through a single draw
        cat = _uniform(1404, rows, 0, 9)
        cls = _uniform(1406, rows, 0, len(CLASSES) - 1)
        brand = _uniform(1407, rows, 1, 500)
        manufact = _uniform(1405, rows, 1, 1000)
        out = {}
        for c in columns:
            if c == "i_item_sk":
                out[c] = rows + 1
            elif c == "i_item_id":
                out[c] = _numbered("Item", self.counts["item"], rows + 1)
            elif c == "i_product_name":
                out[c] = _I_NAME.column(1401, rows)
            elif c == "i_item_desc":
                out[c] = _I_NAME.column(1409, rows)
            elif c == "i_color":
                out[c] = _fixed(
                    COLORS,
                    (_stream(1402, rows) % np.uint64(len(COLORS))).astype(
                        np.int64
                    ),
                )
            elif c == "i_current_price":
                # 50.00..90.00: Q64's price-window parameters select a
                # real slice of items at every scale factor
                out[c] = _uniform(1403, rows, 5000, 9000)
            elif c == "i_category":
                out[c] = _fixed(CATEGORIES, cat)
            elif c == "i_category_id":
                out[c] = cat + 1
            elif c == "i_class":
                out[c] = _fixed(CLASSES, cls)
            elif c == "i_class_id":
                out[c] = cls + 1
            elif c == "i_brand":
                # brand name derived from the same draw as i_brand_id
                # (functional dependence, like dsdgen's brand hierarchy)
                out[c] = _numbered("brand", 500, brand)
            elif c == "i_brand_id":
                out[c] = brand
            elif c == "i_manufact_id":
                out[c] = manufact
            elif c == "i_manufact":
                out[c] = _numbered("manufact", 1000, manufact)
            elif c == "i_size":
                out[c] = _fixed(
                    ["small", "medium", "large", "extra large",
                     "economy", "petite", "N/A"],
                    _uniform(1411, rows, 0, 6),
                )
            elif c == "i_units":
                out[c] = _fixed(
                    ["Each", "Oz", "Pound", "Dozen", "Carton",
                     "Case", "Bunch", "Unknown"],
                    _uniform(1412, rows, 0, 7),
                )
            elif c == "i_manager_id":
                out[c] = _uniform(1408, rows, 1, 100)
            elif c == "i_wholesale_cost":
                # 20.00..80.00, independent of i_current_price like the
                # official generator's separate draw
                out[c] = _uniform(1410, rows, 2000, 8000)
        return out

    def _gen_customer(self, rows, columns):
        cn = self.counts
        out = {}
        for c in columns:
            if c == "c_customer_sk":
                out[c] = rows + 1
            elif c == "c_customer_id":
                out[c] = _numbered("Customer", cn["customer"], rows + 1)
            elif c == "c_first_name":
                out[c] = _fixed(
                    FIRST_NAMES,
                    _uniform(1507, rows, 0, len(FIRST_NAMES) - 1),
                )
            elif c == "c_last_name":
                out[c] = _fixed(
                    LAST_NAMES,
                    _uniform(1508, rows, 0, len(LAST_NAMES) - 1),
                )
            elif c == "c_current_cdemo_sk":
                out[c] = _uniform(
                    1501, rows, 1, cn["customer_demographics"]
                )
            elif c == "c_current_hdemo_sk":
                out[c] = _uniform(
                    1502, rows, 1, cn["household_demographics"]
                )
            elif c == "c_current_addr_sk":
                out[c] = _uniform(1503, rows, 1, cn["customer_address"])
            elif c == "c_first_sales_date_sk":
                out[c] = self._date_sk_for(
                    _uniform(1504, rows, _D_START, _SOLD_HI)
                )
            elif c == "c_first_shipto_date_sk":
                out[c] = self._date_sk_for(
                    _uniform(1505, rows, _D_START, _SOLD_HI)
                )
            elif c == "c_birth_year":
                out[c] = _uniform(1506, rows, 1930, 1990)
            elif c == "c_birth_month":
                out[c] = _uniform(1511, rows, 1, 12)
            elif c == "c_birth_day":
                out[c] = _uniform(1512, rows, 1, 28)
            elif c == "c_birth_country":
                out[c] = _fixed(
                    ["UNITED STATES", "CANADA", "MEXICO", "FRANCE",
                     "GERMANY", "JAPAN", "BRAZIL", "INDIA"],
                    _uniform(1513, rows, 0, 7),
                )
            elif c == "c_login":
                out[c] = _numbered("login", cn["customer"], rows + 1)
            elif c == "c_email_address":
                out[c] = _numbered("email", cn["customer"], rows + 1)
            elif c == "c_last_review_date_sk":
                out[c] = self._date_sk_for(
                    _uniform(1514, rows, _D_START, _SOLD_HI)
                )
            elif c == "c_salutation":
                out[c] = _fixed(
                    ["Mr.", "Mrs.", "Ms.", "Dr.", "Sir", "Miss"],
                    _uniform(1509, rows, 0, 5),
                )
            elif c == "c_preferred_cust_flag":
                out[c] = _fixed(["N", "Y"], _uniform(1510, rows, 0, 1))
        return out

    def _gen_customer_address(self, rows, columns):
        out = {}
        for c in columns:
            if c == "ca_address_sk":
                out[c] = rows + 1
            elif c == "ca_street_number":
                out[c] = _fixed(
                    _STREET_NUMS,
                    _uniform(1601, rows, 0, len(_STREET_NUMS) - 1),
                )
            elif c == "ca_street_name":
                out[c] = _STREET_NAME.column(1602, rows)
            elif c == "ca_city":
                out[c] = _fixed(
                    CITIES, _uniform(1603, rows, 0, len(CITIES) - 1)
                )
            elif c == "ca_state":
                out[c] = _fixed(
                    STATES, _uniform(1604, rows, 0, len(STATES) - 1)
                )
            elif c == "ca_zip":
                out[c] = _fixed(
                    _ZIPS, _uniform(1605, rows, 0, len(_ZIPS) - 1)
                )
            elif c == "ca_county":
                out[c] = _fixed(
                    COUNTIES, _uniform(1606, rows, 0, len(COUNTIES) - 1)
                )
            elif c == "ca_country":
                out[c] = _fixed(["United States"], rows * 0)
            elif c == "ca_street_type":
                out[c] = _fixed(
                    ["Street", "Ave", "Blvd", "Road", "Lane"],
                    _uniform(1608, rows, 0, 4),
                )
            elif c == "ca_suite_number":
                out[c] = _fixed(
                    [f"Suite {n}" for n in range(0, 300, 10)],
                    _uniform(1609, rows, 0, 29),
                )
            elif c == "ca_location_type":
                out[c] = _fixed(
                    ["apartment", "condo", "single family"],
                    _uniform(1610, rows, 0, 2),
                )
            elif c == "ca_gmt_offset":
                # continental offsets; -5 is the modal official
                # substitution value so it must select a real slice
                out[c] = np.asarray([-5, -5, -6, -7, -8], np.int64)[
                    _uniform(1607, rows, 0, 4)
                ]
        return out

    # -- fact tables --------------------------------------------------

    def _ss_fields(self, rows):
        """Shared store_sales row fields (store_returns derives from the
        same closed forms via its row map, keeping FK pairs exact)."""
        cn = self.counts
        ticket, _line = _order_of_row(rows)
        return {
            "ticket": ticket + 1,
            "item": _uniform(1701, rows, 1, cn["item"]),
            "sold": _uniform(1702, rows, _SOLD_LO, _SOLD_HI),
        }

    def _gen_store_sales(self, rows, columns):
        cn = self.counts
        f = self._ss_fields(rows)
        # hoisted shared draws: per-unit and extended columns must stay
        # row-wise consistent, so each quantity/price stream is drawn
        # exactly once here (the consistency invariant lives in these
        # bindings, not in matching magic tags across branches)
        wholesale = _uniform(1703, rows, 100, 10000)
        quantity = _uniform(1710, rows, 1, 100)
        list_price = wholesale + _uniform(1711, rows, 0, 5000)
        sales_price = _uniform(1714, rows, 50, 9900)
        out = {}
        for c in columns:
            if c == "ss_sold_date_sk":
                out[c] = self._date_sk_for(f["sold"])
            elif c == "ss_item_sk":
                out[c] = f["item"]
            elif c == "ss_customer_sk":
                # drawn from the TICKET, not the row: every line of a
                # ticket belongs to one customer (official dsdgen;
                # Q34-class per-ticket counts join this to customer)
                out[c] = _uniform(1704, f["ticket"], 1, cn["customer"])
            elif c == "ss_cdemo_sk":
                out[c] = _uniform(
                    1705, rows, 1, cn["customer_demographics"]
                )
            elif c == "ss_hdemo_sk":
                out[c] = _uniform(
                    1706, rows, 1, cn["household_demographics"]
                )
            elif c == "ss_addr_sk":
                out[c] = _uniform(1707, rows, 1, cn["customer_address"])
            elif c == "ss_store_sk":
                out[c] = _uniform(1708, rows, 1, cn["store"])
            elif c == "ss_promo_sk":
                out[c] = _uniform(1709, rows, 1, cn["promotion"])
            elif c == "ss_ticket_number":
                out[c] = f["ticket"]
            elif c == "ss_quantity":
                out[c] = quantity
            elif c == "ss_wholesale_cost":
                out[c] = wholesale
            elif c == "ss_list_price":
                out[c] = list_price
            elif c == "ss_sales_price":
                out[c] = sales_price
            elif c == "ss_ext_sales_price":
                # sales_price * quantity, in cents; max 99.00 * 100 =
                # 9900.00, inside decimal(7,2)
                out[c] = sales_price * quantity
            elif c == "ss_ext_list_price":
                # list_price * quantity <= 150.00 * 100, inside d(7,2)
                out[c] = list_price * quantity
            elif c == "ss_ext_tax":
                out[c] = _uniform(1715, rows, 0, 90000)
            elif c == "ss_coupon_amt":
                out[c] = _sparse_amount(1712, 1713, rows)
            elif c == "ss_net_profit":
                out[c] = _uniform(1716, rows, -500000, 1000000)
            elif c == "ss_ext_discount_amt":
                out[c] = _uniform(1717, rows, 0, 100000)
            elif c == "ss_ext_wholesale_cost":
                out[c] = wholesale * quantity
            elif c == "ss_net_paid":
                # quantity * sales_price, from the SAME hoisted draws
                out[c] = quantity * sales_price
            elif c == "ss_sold_time_sk":
                out[c] = _uniform(1718, rows, 0, 86399)
        return out

    def _gen_store_returns(self, rows, columns):
        src = rows * 2  # return j <-> store_sales row 2j
        f = self._ss_fields(src)
        out = {}
        for c in columns:
            if c == "sr_returned_date_sk":
                out[c] = self._date_sk_for(
                    f["sold"] + _uniform(1801, rows, 1, 90)
                )
            elif c == "sr_item_sk":
                out[c] = f["item"]
            elif c == "sr_ticket_number":
                out[c] = f["ticket"]
            elif c == "sr_return_amt":
                out[c] = _uniform(1802, rows, 100, 10000)
            elif c == "sr_net_loss":
                out[c] = _uniform(1803, rows, 100, 8000)
            elif c == "sr_store_sk":
                # SAME closed form store_sales evaluates at the source
                # row: the (ticket, item) FK pair stays store-consistent
                out[c] = _uniform(1708, src, 1, self.counts["store"])
            elif c == "sr_customer_sk":
                # SAME ticket-keyed closed form store_sales evaluates
                # at the source row, so (ticket, customer) stays exact
                out[c] = _uniform(
                    1704, f["ticket"], 1, self.counts["customer"]
                )
            elif c == "sr_return_quantity":
                out[c] = _uniform(1805, rows, 1, 40)
            elif c == "sr_reason_sk":
                out[c] = _uniform(
                    1806, rows, 1, self.counts["reason"]
                )
            elif c == "sr_cdemo_sk":
                out[c] = _uniform(
                    1807, rows, 1, self.counts["customer_demographics"]
                )
        return out

    def _cs_fields(self, rows):
        cn = self.counts
        order, _line = _order_of_row(rows)
        return {
            "order": order + 1,
            "item": _uniform(1901, rows, 1, cn["item"]),
            "sold": _uniform(1902, rows, _SOLD_LO, _SOLD_HI),
        }

    def _gen_catalog_sales(self, rows, columns):
        cn = self.counts
        f = self._cs_fields(rows)
        out = {}
        for c in columns:
            if c == "cs_sold_date_sk":
                out[c] = self._date_sk_for(f["sold"])
            elif c == "cs_ship_date_sk":
                # 1..120-day ship lag: Q99's latency buckets all select
                out[c] = self._date_sk_for(
                    f["sold"] + _uniform(1912, rows, 1, 120)
                )
            elif c == "cs_ship_mode_sk":
                out[c] = _uniform(1913, rows, 1, cn["ship_mode"])
            elif c == "cs_call_center_sk":
                out[c] = _uniform(1914, rows, 1, cn["call_center"])
            elif c == "cs_warehouse_sk":
                out[c] = _uniform(1915, rows, 1, cn["warehouse"])
            elif c == "cs_bill_customer_sk":
                # ORDER-keyed: every line of an order bills one
                # customer (official dsdgen; matches ws/ss channels)
                out[c] = _uniform(1903, f["order"], 1, cn["customer"])
            elif c == "cs_bill_cdemo_sk":
                out[c] = _uniform(
                    1906, rows, 1, cn["customer_demographics"]
                )
            elif c == "cs_item_sk":
                out[c] = f["item"]
            elif c == "cs_promo_sk":
                out[c] = _uniform(1907, rows, 1, cn["promotion"])
            elif c == "cs_order_number":
                out[c] = f["order"]
            elif c == "cs_quantity":
                out[c] = _uniform(1904, rows, 1, 100)
            elif c == "cs_list_price":
                # sales <= list, like the store channel's
                # wholesale-plus-delta invariant
                out[c] = _uniform(1909, rows, 50, 9900) + _uniform(
                    1908, rows, 0, 5100
                )
            elif c == "cs_sales_price":
                out[c] = _uniform(1909, rows, 50, 9900)
            elif c == "cs_coupon_amt":
                out[c] = _sparse_amount(1910, 1911, rows)
            elif c == "cs_ext_list_price":
                out[c] = _uniform(1905, rows, 10000, 100000)
            elif c == "cs_ext_sales_price":
                out[c] = _uniform(1916, rows, 100, 30000)
            elif c == "cs_bill_hdemo_sk":
                out[c] = _uniform(
                    1920, rows, 1, cn["household_demographics"]
                )
            elif c == "cs_net_profit":
                out[c] = _uniform(1917, rows, -5000, 20000)
            elif c == "cs_catalog_page_sk":
                out[c] = _uniform(
                    1918, rows, 1, cn["catalog_page"]
                )
            elif c == "cs_ext_discount_amt":
                out[c] = _uniform(1921, rows, 0, 100000)
            elif c == "cs_wholesale_cost":
                out[c] = _uniform(1929, rows, 100, 10000)
            elif c == "cs_ext_ship_cost":
                out[c] = _uniform(1922, rows, 100, 10000)
            elif c == "cs_ext_wholesale_cost":
                out[c] = _uniform(1923, rows, 100, 1000000)
            elif c == "cs_net_paid":
                out[c] = _uniform(1924, rows, 100, 300000)
            elif c == "cs_ship_addr_sk":
                out[c] = _uniform(
                    1925, rows, 1, cn["customer_address"]
                )
            elif c == "cs_bill_addr_sk":
                # order-keyed like the bill customer: one address per
                # order (q33/q56/q60 group channel revenue by it)
                out[c] = _uniform(
                    1926, f["order"], 1, cn["customer_address"]
                )
            elif c == "cs_ship_customer_sk":
                out[c] = _uniform(1927, rows, 1, cn["customer"])
            elif c == "cs_sold_time_sk":
                out[c] = _uniform(1928, rows, 0, 86399)
        return out

    def _gen_catalog_returns(self, rows, columns):
        src = rows * 2  # return j <-> catalog_sales row 2j
        f = self._cs_fields(src)
        out = {}
        for c in columns:
            if c == "cr_returned_date_sk":
                out[c] = self._date_sk_for(
                    f["sold"] + _uniform(2001, rows, 1, 90)
                )
            elif c == "cr_item_sk":
                out[c] = f["item"]
            elif c == "cr_order_number":
                out[c] = f["order"]
            elif c == "cr_refunded_cash":
                # bounded well below cs_ext_list_price so Q64's cs_ui
                # HAVING (sale > 2*refund) keeps a healthy item fraction
                out[c] = _uniform(2002, rows, 0, 15000)
            elif c == "cr_reversed_charge":
                out[c] = _uniform(2003, rows, 0, 5000)
            elif c == "cr_store_credit":
                out[c] = _uniform(2004, rows, 0, 5000)
            elif c == "cr_return_amount":
                out[c] = _uniform(2005, rows, 100, 10000)
            elif c == "cr_net_loss":
                out[c] = _uniform(2006, rows, 100, 8000)
            elif c == "cr_catalog_page_sk":
                # SAME closed form catalog_sales evaluates at the
                # source row: a return's page is its sale's page
                out[c] = _uniform(
                    1918, src, 1, self.counts["catalog_page"]
                )
            elif c == "cr_return_quantity":
                out[c] = _uniform(2008, rows, 1, 40)
            elif c == "cr_returning_customer_sk":
                # usually the billing customer of the source sale,
                # sometimes a different party (official mix); 1903 is
                # catalog_sales' ORDER-keyed bill-customer closed form
                bill = _uniform(
                    1903, f["order"], 1, self.counts["customer"]
                )
                other = _uniform(
                    2009, rows, 1, self.counts["customer"]
                )
                out[c] = np.where(
                    _uniform(2010, rows, 0, 9) < 8, bill, other
                )
            elif c == "cr_returning_addr_sk":
                out[c] = _uniform(
                    2011, rows, 1, self.counts["customer_address"]
                )
            elif c == "cr_call_center_sk":
                out[c] = _uniform(
                    2012, rows, 1, self.counts["call_center"]
                )
        return out

    def _ws_fields(self, rows):
        cn = self.counts
        order, _line = _order_of_row(rows)
        sold = _uniform(2101, rows, _SOLD_LO, _SOLD_HI)
        return {
            "order": order + 1,
            "item": _uniform(2102, rows, 1, cn["item"]),
            "sold": sold,
        }

    def _gen_web_sales(self, rows, columns):
        cn = self.counts
        f = self._ws_fields(rows)
        out = {}
        for c in columns:
            if c == "ws_sold_date_sk":
                out[c] = self._date_sk_for(f["sold"])
            elif c == "ws_ship_date_sk":
                out[c] = self._date_sk_for(
                    f["sold"] + _uniform(2103, rows, 1, 30)
                )
            elif c == "ws_item_sk":
                out[c] = f["item"]
            elif c == "ws_ship_addr_sk":
                out[c] = _uniform(2104, rows, 1, cn["customer_address"])
            elif c == "ws_web_site_sk":
                out[c] = _uniform(2105, rows, 1, cn["web_site"])
            elif c == "ws_warehouse_sk":
                # 3 warehouses in rotation: multi-line orders usually mix
                # warehouses, so Q95's ws_wh self-join inequality selects
                # a real slice
                out[c] = _uniform(2106, rows, 1, 3)
            elif c == "ws_ship_mode_sk":
                out[c] = _uniform(2109, rows, 1, cn["ship_mode"])
            elif c == "ws_ext_sales_price":
                out[c] = _uniform(2110, rows, 100, 30000)
            elif c == "ws_order_number":
                out[c] = f["order"]
            elif c == "ws_ext_ship_cost":
                out[c] = _uniform(2107, rows, 100, 10000)
            elif c == "ws_net_profit":
                out[c] = _uniform(2108, rows, -5000, 20000)
            elif c == "ws_bill_customer_sk":
                # drawn from the ORDER number, not the row: every line
                # of an order bills the same customer (q38/q87/q97
                # count distinct customers per channel)
                out[c] = _uniform(2111, f["order"], 1, cn["customer"])
            elif c == "ws_bill_addr_sk":
                out[c] = _uniform(
                    2112, f["order"], 1, cn["customer_address"]
                )
            elif c == "ws_web_page_sk":
                out[c] = _uniform(2113, rows, 1, cn["web_page"])
            elif c == "ws_promo_sk":
                out[c] = _uniform(2115, rows, 1, cn["promotion"])
            elif c == "ws_sales_price":
                out[c] = _uniform(2116, rows, 50, 9900)
            elif c == "ws_quantity":
                out[c] = _uniform(2117, rows, 1, 100)
            elif c == "ws_list_price":
                out[c] = _uniform(2118, rows, 100, 15000)
            elif c == "ws_wholesale_cost":
                out[c] = _uniform(2119, rows, 100, 10000)
            elif c == "ws_ext_discount_amt":
                out[c] = _uniform(2120, rows, 0, 100000)
            elif c == "ws_ext_wholesale_cost":
                out[c] = _uniform(2121, rows, 100, 1000000)
            elif c == "ws_ext_list_price":
                out[c] = _uniform(2122, rows, 100, 1500000)
            elif c == "ws_net_paid":
                out[c] = _uniform(2123, rows, 100, 990000)
            elif c == "ws_sold_time_sk":
                out[c] = _uniform(2124, rows, 0, 86399)
            elif c == "ws_ship_hdemo_sk":
                out[c] = _uniform(
                    2125, rows, 1, cn["household_demographics"]
                )
        return out

    def _gen_web_returns(self, rows, columns):
        src = rows * 3  # return j <-> web_sales row 3j
        f = self._ws_fields(src)
        out = {}
        for c in columns:
            if c == "wr_returned_date_sk":
                out[c] = self._date_sk_for(
                    f["sold"] + _uniform(2201, rows, 1, 90)
                )
            elif c == "wr_item_sk":
                out[c] = f["item"]
            elif c == "wr_order_number":
                out[c] = f["order"]
            elif c == "wr_return_amt":
                out[c] = _uniform(2202, rows, 100, 10000)
            elif c == "wr_net_loss":
                out[c] = _uniform(2203, rows, 100, 8000)
            elif c == "wr_web_page_sk":
                # source web_sales row's page (same closed form)
                out[c] = _uniform(
                    2113, src, 1, self.counts["web_page"]
                )
            elif c == "wr_return_quantity":
                out[c] = _uniform(2205, rows, 1, 40)
            elif c == "wr_returning_customer_sk":
                bill = _uniform(
                    2111, f["order"], 1, self.counts["customer"]
                )
                other = _uniform(
                    2206, rows, 1, self.counts["customer"]
                )
                out[c] = np.where(
                    _uniform(2207, rows, 0, 9) < 8, bill, other
                )
            elif c == "wr_returning_addr_sk":
                out[c] = _uniform(
                    2208, rows, 1, self.counts["customer_address"]
                )
            elif c == "wr_refunded_cash":
                out[c] = _uniform(2209, rows, 0, 15000)
            elif c == "wr_reason_sk":
                out[c] = _uniform(
                    2210, rows, 1, self.counts["reason"]
                )
            elif c == "wr_refunded_cdemo_sk":
                out[c] = _uniform(
                    2211, rows, 1, self.counts["customer_demographics"]
                )
            elif c == "wr_refunded_addr_sk":
                out[c] = _uniform(
                    2212, rows, 1, self.counts["customer_address"]
                )
            elif c == "wr_returning_cdemo_sk":
                out[c] = _uniform(
                    2213, rows, 1, self.counts["customer_demographics"]
                )
            elif c == "wr_fee":
                out[c] = _uniform(2214, rows, 50, 10000)
        return out


# -------------------------------------------------------------- connector


#: value-range stats for date-dimension attributes (the calendar is a
#: known domain): lets grouped CTE outputs keyed on d_year pack into
#: composite join keys, and sharpens range selectivities
_DATE_COL_STATS = {
    "d_year": ColumnStats(distinct_count=14, min_value=1990, max_value=2003),
    "d_moy": ColumnStats(distinct_count=12, min_value=1, max_value=12),
    "d_qoy": ColumnStats(distinct_count=4, min_value=1, max_value=4),
    "d_dom": ColumnStats(distinct_count=31, min_value=1, max_value=31),
    "d_dow": ColumnStats(distinct_count=7, min_value=0, max_value=6),
    "d_week_seq": ColumnStats(
        distinct_count=731, min_value=1043, max_value=1774
    ),
    "d_month_seq": ColumnStats(
        distinct_count=168, min_value=1080, max_value=1247
    ),
}


class _TpcdsMetadata(ConnectorMetadata):
    PRIMARY_KEYS = {
        "date_dim": ("d_date_sk",),
        "income_band": ("ib_income_band_sk",),
        "customer_demographics": ("cd_demo_sk",),
        "household_demographics": ("hd_demo_sk",),
        "warehouse": ("w_warehouse_sk",),
        "web_site": ("web_site_sk",),
        "store": ("s_store_sk",),
        "promotion": ("p_promo_sk",),
        "item": ("i_item_sk",),
        "customer": ("c_customer_sk",),
        "customer_address": ("ca_address_sk",),
        "time_dim": ("t_time_sk",),
        "reason": ("r_reason_sk",),
        "ship_mode": ("sm_ship_mode_sk",),
        "call_center": ("cc_call_center_sk",),
        "web_page": ("wp_web_page_sk",),
        "catalog_page": ("cp_catalog_page_sk",),
        # fact tables: NO primary key declared — the closed-form
        # generators draw items independently per line, so (item, order)
        # pairs can repeat; declaring a PK would license build-unique
        # join plans that those duplicates would silently break
    }

    FOREIGN_KEYS = {
        "ss_item_sk": "item", "ss_customer_sk": "customer",
        "ss_cdemo_sk": "customer_demographics",
        "ss_hdemo_sk": "household_demographics",
        "ss_addr_sk": "customer_address", "ss_store_sk": "store",
        "ss_promo_sk": "promotion",
        "sr_item_sk": "item",
        "cs_item_sk": "item", "cs_bill_customer_sk": "customer",
        "cs_bill_cdemo_sk": "customer_demographics",
        "cs_promo_sk": "promotion",
        "cr_item_sk": "item",
        "ws_item_sk": "item", "ws_ship_addr_sk": "customer_address",
        "ws_web_site_sk": "web_site", "ws_warehouse_sk": "warehouse",
        "wr_item_sk": "item",
        "c_current_cdemo_sk": "customer_demographics",
        "c_current_hdemo_sk": "household_demographics",
        "c_current_addr_sk": "customer_address",
        "hd_income_band_sk": "income_band",
        "inv_item_sk": "item",
        "inv_warehouse_sk": "warehouse",
        "ws_ship_mode_sk": "ship_mode",
        "cs_ship_mode_sk": "ship_mode",
        "cs_call_center_sk": "call_center",
        "cs_warehouse_sk": "warehouse",
        # round-5 columns (stats keep the optimizer's NDV formula and
        # output-capacity sizing honest — a stats-less fan-in key
        # otherwise defaults to the no-info path)
        "sr_customer_sk": "customer",
        "sr_store_sk": "store",
        "sr_reason_sk": "reason",
        "sr_cdemo_sk": "customer_demographics",
        "cs_bill_hdemo_sk": "household_demographics",
        "cs_catalog_page_sk": "catalog_page",
        "cs_ship_addr_sk": "customer_address",
        "cs_bill_addr_sk": "customer_address",
        "cs_ship_customer_sk": "customer",
        "cr_catalog_page_sk": "catalog_page",
        "cr_returning_customer_sk": "customer",
        "cr_returning_addr_sk": "customer_address",
        "cr_call_center_sk": "call_center",
        "ws_bill_customer_sk": "customer",
        "ws_bill_addr_sk": "customer_address",
        "ws_web_page_sk": "web_page",
        "ws_promo_sk": "promotion",
        "ws_ship_hdemo_sk": "household_demographics",
        "wr_returning_customer_sk": "customer",
        "wr_returning_addr_sk": "customer_address",
        "wr_web_page_sk": "web_page",
        "wr_reason_sk": "reason",
    }

    #: 0-based time surrogate keys (t_time_sk = 0..86399): packed
    #: separately so min/max stats stay exact for bijective key packing
    TIME_FKS = ("ss_sold_time_sk", "cs_sold_time_sk", "ws_sold_time_sk")

    DATE_FKS = (
        "ss_sold_date_sk", "sr_returned_date_sk", "cs_sold_date_sk",
        "cr_returned_date_sk", "ws_sold_date_sk", "ws_ship_date_sk",
        "wr_returned_date_sk", "c_first_sales_date_sk",
        "c_first_shipto_date_sk", "inv_date_sk", "cs_ship_date_sk",
    )

    def list_schemas(self):
        return list(SCHEMAS)

    def list_tables(self, schema):
        return list(TABLE_SCHEMAS)

    def get_table_schema(self, handle: TableHandle):
        if handle.schema not in SCHEMAS:
            raise KeyError(f"unknown tpcds schema: {handle.schema}")
        if handle.table not in TABLE_SCHEMAS:
            raise KeyError(f"unknown tpcds table: {handle.table}")
        return dict(TABLE_SCHEMAS[handle.table])

    def get_table_stats(self, handle: TableHandle):
        counts = _counts(SCHEMAS[handle.schema])
        n = counts[handle.table]
        pk = self.PRIMARY_KEYS.get(handle.table)
        cols: Dict[str, ColumnStats] = {}
        for name in TABLE_SCHEMAS[handle.table]:
            if pk and len(pk) == 1 and name == pk[0]:
                cols[name] = ColumnStats(
                    distinct_count=n, min_value=1, max_value=n
                )
            elif name in self.DATE_FKS:
                cols[name] = ColumnStats(
                    distinct_count=min(N_DATES, n),
                    min_value=_DATE_SK0,
                    max_value=_DATE_SK0 + N_DATES - 1,
                )
            elif name in self.TIME_FKS:
                cols[name] = ColumnStats(
                    distinct_count=min(86_400, n),
                    min_value=0,
                    max_value=86_399,
                )
            elif handle.table == "date_dim" and name in _DATE_COL_STATS:
                cols[name] = _DATE_COL_STATS[name]
            elif name in self.FOREIGN_KEYS:
                ref = counts[self.FOREIGN_KEYS[name]]
                cols[name] = ColumnStats(
                    distinct_count=min(ref, n), min_value=1, max_value=ref
                )
        return TableStats(row_count=float(n), columns=cols, primary_key=pk)


class TpcdsConnector(Connector):
    """Catalog 'tpcds': schemas tiny/sf1/sf10/sf100, zero stored bytes."""

    def __init__(self, **config):
        self._metadata = _TpcdsMetadata()
        self._gens: Dict[str, TpcdsGenerator] = {}

    def metadata(self):
        return self._metadata

    def _gen(self, schema: str) -> TpcdsGenerator:
        if schema not in self._gens:
            self._gens[schema] = TpcdsGenerator(SCHEMAS[schema])
        return self._gens[schema]

    def get_splits(self, handle: TableHandle, target_split_rows: int = 1 << 20, constraint=()):
        n = self._gen(handle.schema).counts[handle.table]
        splits = [
            ConnectorSplit(handle, lo, min(lo + target_split_rows, n))
            for lo in range(0, n, target_split_rows)
        ] or [ConnectorSplit(handle, 0, 0)]
        return SplitSource(splits)

    def create_page_source(self, split: ConnectorSplit, columns):
        return self._gen(split.table.schema).generate(
            split.table.table, split.row_start, split.row_end, columns
        )
