"""Parquet connector: columnar files on disk as queryable tables.

Reference parity: ``presto-parquet`` + the hive-style file connector
surface (SURVEY.md §2.2 L9 "file-format readers") — columnar reads with
column pruning, row-group splits, and statistics from file metadata
(row counts + per-column min/max feed the cost-based optimizer exactly
like the reference's TupleDomain pruning inputs).

TPU-first shape: the reader produces the engine's staging payloads
directly — numeric numpy arrays in native representation (decimals as
scaled int64, dates as epoch days) and strings pre-encoded as
dictionary ids (strings never touch the device; SURVEY.md §7 "Strings
on TPU"). Arrow's columnar layout makes this a zero-copy handoff for
the numeric columns.

Layout: ``root/<schema>/<table>.parquet``. With the ``lakehouse``
config (a manifest-store root), the catalog ADDITIONALLY serves
manifest-committed snapshot tables — versioned, time-travelable, and
writable through the ingest lane — via the shared lakehouse surface
in ``server/manifests.py``; plain file tables stay bit-exact legacy.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from presto_tpu import types as T
from presto_tpu.connectors._arrow import (
    arrow_column_to_payload as _arrow_column_to_payload,
    arrow_to_engine_type as _arrow_to_engine_type,
)
from presto_tpu.connectors.spi import (
    ColumnStats,
    Connector,
    ConnectorMetadata,
    ConnectorSplit,
    RangeSet,
    SplitSource,
    TableHandle,
    TableStats,
)
from presto_tpu.server.manifests import LakehouseConnectorMixin


def rowgroup_matches(stats, domain) -> bool:
    """May a row group with ``stats`` (pyarrow column statistics, or
    None) contain rows satisfying ``domain`` (a value tuple or a
    dynamic-filter :class:`RangeSet`)? Missing/non-numeric stats keep
    the group — over-retain, never over-prune (the originating filter
    still applies to every row read)."""
    if isinstance(domain, RangeSet):
        if (
            stats is None
            or not stats.has_min_max
            or not isinstance(stats.min, (int, float))
            or isinstance(stats.min, bool)
            or not isinstance(domain.lo, (int, float))
        ):
            return True
        return not (stats.max < domain.lo or stats.min > domain.hi)
    # value set: an EMPTY set matches nothing (empty build side)
    if not domain:
        return False
    if (
        stats is None
        or not stats.has_min_max
        or not isinstance(stats.min, (int, float))
        or isinstance(stats.min, bool)
    ):
        return True
    vals = [v for v in domain if isinstance(v, (int, float))]
    if len(vals) != len(domain):
        return True  # non-numeric literals: don't prune on them
    return any(stats.min <= v <= stats.max for v in vals)


class _ParquetMetadata(ConnectorMetadata):
    def __init__(self, conn: "ParquetConnector"):
        self._conn = conn

    def list_schemas(self) -> List[str]:
        root = self._conn.root
        out = set(self._conn.lake_list_schemas())
        try:
            out.update(
                d
                for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d))
            )
        except OSError:
            pass
        return sorted(out)

    def list_tables(self, schema: str) -> List[str]:
        d = os.path.join(self._conn.root, schema)
        out = set(self._conn.lake_list_tables(schema))
        try:
            out.update(
                fn[: -len(".parquet")]
                for fn in os.listdir(d)
                if fn.endswith(".parquet")
            )
        except OSError:
            pass
        return sorted(out)

    def get_table_schema(self, handle: TableHandle) -> Dict[str, T.DataType]:
        lake = self._conn.lake_schema(handle)
        if lake is not None:
            return lake
        pf = self._conn._file(handle)
        return {
            f.name: _arrow_to_engine_type(f.type)
            for f in pf.schema_arrow
        }

    def get_table_stats(self, handle: TableHandle) -> TableStats:
        """Row count + per-column min/max straight from the parquet
        footer (zero data reads) — the optimizer's range-selectivity
        and join-sizing inputs. Manifest-backed tables answer from
        the pinned manifest instead (same inputs, zero file opens)."""
        lake = self._conn.lake_table_stats(handle)
        if lake is not None:
            return lake
        pf = self._conn._file(handle)
        md = pf.metadata
        cols: Dict[str, ColumnStats] = {}
        schema = self.get_table_schema(handle)
        mins: Dict[str, object] = {}
        maxs: Dict[str, object] = {}
        ndv: Dict[str, float] = {}
        for rg in range(md.num_row_groups):
            g = md.row_group(rg)
            for ci in range(g.num_columns):
                c = g.column(ci)
                st = c.statistics
                name = c.path_in_schema
                if st is None or not st.has_min_max:
                    continue
                if not isinstance(st.min, (int, float)):
                    continue  # numeric ranges only
                mins[name] = (
                    st.min if name not in mins else min(mins[name], st.min)
                )
                maxs[name] = (
                    st.max if name not in maxs else max(maxs[name], st.max)
                )
                if st.distinct_count:
                    ndv[name] = ndv.get(name, 0.0) + st.distinct_count
        for name in schema:
            if name in mins:
                cols[name] = ColumnStats(
                    distinct_count=ndv.get(name),
                    min_value=float(mins[name]),
                    max_value=float(maxs[name]),
                )
        return TableStats(row_count=float(md.num_rows), columns=cols)


class ParquetConnector(LakehouseConnectorMixin, Connector):
    """Catalog over ``root/<schema>/<table>.parquet`` files, plus
    manifest-backed snapshot tables when ``lakehouse`` is set."""

    def prunes_splits(self) -> bool:
        return True  # row-group footer min/max prune splits

    def __init__(
        self,
        root: str = ".",
        lakehouse: Optional[str] = None,
        catalog: Optional[str] = None,
        target_file_bytes: Optional[int] = None,
        **config,
    ):
        self.root = root
        self._metadata = _ParquetMetadata(self)
        self._files: Dict[TableHandle, object] = {}
        self._init_lakehouse(
            lakehouse, catalog=catalog,
            target_file_bytes=target_file_bytes,
        )

    def metadata(self):
        return self._metadata

    def _path(self, handle: TableHandle) -> str:
        return os.path.join(
            self.root, handle.schema, handle.table + ".parquet"
        )

    def _file(self, handle: TableHandle):
        import pyarrow.parquet as pq

        pf = self._files.get(handle)
        if pf is None:
            path = self._path(handle)
            if not os.path.exists(path):
                raise KeyError(f"no parquet table at {path}")
            pf = pq.ParquetFile(path)
            self._files[handle] = pf
        return pf

    def get_splits(
        self, handle: TableHandle, target_split_rows: int = 1 << 20,
        constraint=(),
    ) -> SplitSource:
        """Row-group-aligned splits (the reference's parquet split
        boundary); expressed as row ranges so the engine's split
        protocol stays format-agnostic. Row groups whose footer
        min/max statistics cannot satisfy the pushed ``constraint``
        (dynamic-filter RangeSets / value sets) produce no splits —
        those rows are never read. Manifest-backed tables prune at
        the FILE level from manifest min/max instead."""
        lake = self.lake_splits(handle, target_split_rows, constraint)
        if lake is not None:
            return lake
        pf = self._file(handle)
        md = pf.metadata
        # constraint column -> row-group column index (once per call)
        col_idx: Dict[str, int] = {}
        if constraint and md.num_row_groups:
            g0 = md.row_group(0)
            names = {
                g0.column(ci).path_in_schema: ci
                for ci in range(g0.num_columns)
            }
            col_idx = {
                col: names[col]
                for col, _ in constraint
                if col in names
            }

        def rg_matches(rg: int) -> bool:
            if not col_idx:
                return True
            g = md.row_group(rg)
            for col, dom in constraint:
                ci = col_idx.get(col)
                st = (
                    g.column(ci).statistics if ci is not None else None
                )
                if not rowgroup_matches(st, dom):
                    return False
            return True

        from presto_tpu.connectors.spi import coalesce_kept_chunks

        chunk_rows = [
            md.row_group(rg).num_rows
            for rg in range(md.num_row_groups)
        ]
        keep = [rg_matches(rg) for rg in range(md.num_row_groups)]
        return SplitSource(
            coalesce_kept_chunks(
                handle, chunk_rows, keep, target_split_rows
            )
        )

    def create_page_source(
        self, split: ConnectorSplit, columns: Sequence[str]
    ) -> Dict[str, object]:
        import pyarrow.parquet as pq

        lake = self.lake_page_source(split, columns)
        if lake is not None:
            return lake
        pf = self._file(split.table)
        schema = self._metadata.get_table_schema(split.table)
        # map the row range back onto row groups, then TRIM the read to
        # exactly [row_start, row_end) — the split contract is a row
        # range, and the worker batches scans at arbitrary boundaries
        md = pf.metadata
        groups: List[int] = []
        lo = 0
        first_lo = 0
        for rg in range(md.num_row_groups):
            n = md.row_group(rg).num_rows
            if lo < split.row_end and lo + n > split.row_start:
                if not groups:
                    first_lo = lo
                groups.append(rg)
            lo += n
        table = pf.read_row_groups(groups, columns=list(columns))
        a = split.row_start - first_lo
        b = split.row_end - first_lo
        table = table.slice(a, b - a)
        out: Dict[str, object] = {}
        for name in columns:
            arr = table.column(name)
            out[name] = _arrow_column_to_payload(arr, schema[name])
        return out
