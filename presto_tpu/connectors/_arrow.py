"""Shared Arrow-to-engine conversion for file-format connectors.

Both columnar file formats the engine reads (parquet, ORC) arrive
through pyarrow, and both hand the engine the same staging payloads:
numeric numpy arrays in the engine's native representation (decimals as
scaled int64, dates as epoch days, timestamps as epoch micros) and
strings pre-encoded as dictionary ids — strings never touch the device
(SURVEY.md §7 "Strings on TPU"). Reference parity: the format readers
under ``presto-parquet`` / ``presto-orc`` share the column-reader
contract the same way (SURVEY.md §2.2 L9).
"""

from __future__ import annotations

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.tpch import DictColumn
from presto_tpu.exec.staging import MaskedColumn


def arrow_to_engine_type(at) -> T.DataType:
    import pyarrow as pa

    if pa.types.is_boolean(at):
        return T.BOOLEAN
    if pa.types.is_integer(at):
        return T.BIGINT if at.bit_width > 32 else T.INTEGER
    if pa.types.is_floating(at):
        return T.DOUBLE
    if pa.types.is_decimal(at):
        # T.decimal routes p>18 to the int128-limbed LongDecimalType
        return T.decimal(at.precision, at.scale)
    if pa.types.is_date(at):
        return T.DATE
    if pa.types.is_timestamp(at):
        return T.TIMESTAMP
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return T.VARCHAR
    raise NotImplementedError(f"no engine mapping for arrow type {at}")


def arrow_column_to_payload(arr, t: T.DataType):
    """Arrow chunked array -> engine staging payload."""
    import pyarrow as pa

    combined = arr.combine_chunks()
    nulls = combined.null_count > 0
    if t.is_string:
        ids, valid, dictionary = _encode_arrow_strings(combined)
        if nulls:
            return MaskedColumn(
                data=ids, valid=valid, values=tuple(dictionary)
            )
        return DictColumn(
            ids=ids, values=np.asarray(dictionary, dtype=object)
        )
    if t.is_decimal:
        # arrow decimal128 -> unscaled int64 (short) or (n, 2) int128
        # limb pairs (long)
        unscaled = [
            0 if v is None else int(v.as_py().scaleb(t.scale))
            for v in combined
        ]
        if t.is_long_decimal:
            data = T.int128_limbs(unscaled)
        else:
            data = np.asarray(unscaled, dtype=np.int64)
    elif t.name == "date":
        data = np.asarray(
            combined.cast(pa.int32()).fill_null(0), dtype=np.int64
        )
    elif t.name == "timestamp":
        data = np.asarray(
            combined.cast(pa.int64()).fill_null(0), dtype=np.int64
        )
    else:
        data = np.asarray(
            combined.fill_null(0), dtype=t.np_dtype
        )
    if not nulls:
        return data
    valid = np.asarray(combined.is_valid(), dtype=bool)
    return MaskedColumn(data=data, valid=valid)


def _encode_arrow_strings(combined):
    """Arrow string column -> (int32 ids, valid, sorted dictionary)."""
    valid = np.asarray(combined.is_valid(), dtype=bool)
    values = combined.fill_null("").to_numpy(zero_copy_only=False)
    values = values.astype(object)
    present = values[valid].astype(str)
    uniq = np.unique(present) if len(present) else np.empty(0, object)
    ids = np.zeros(len(values), dtype=np.int32)
    if len(present):
        ids[valid] = np.searchsorted(
            uniq.astype(str), present
        ).astype(np.int32)
    return ids, valid, uniq.astype(object)
