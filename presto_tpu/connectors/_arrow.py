"""Shared Arrow-to-engine conversion for file-format connectors.

Both columnar file formats the engine reads (parquet, ORC) arrive
through pyarrow, and both hand the engine the same staging payloads:
numeric numpy arrays in the engine's native representation (decimals as
scaled int64, dates as epoch days, timestamps as epoch micros) and
strings pre-encoded as dictionary ids — strings never touch the device
(SURVEY.md §7 "Strings on TPU"). Reference parity: the format readers
under ``presto-parquet`` / ``presto-orc`` share the column-reader
contract the same way (SURVEY.md §2.2 L9).
"""

from __future__ import annotations

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.tpch import DictColumn
from presto_tpu.exec.staging import MaskedColumn


def arrow_to_engine_type(at) -> T.DataType:
    import pyarrow as pa

    if pa.types.is_boolean(at):
        return T.BOOLEAN
    if pa.types.is_integer(at):
        return T.BIGINT if at.bit_width > 32 else T.INTEGER
    if pa.types.is_floating(at):
        return T.DOUBLE
    if pa.types.is_decimal(at):
        # T.decimal routes p>18 to the int128-limbed LongDecimalType
        return T.decimal(at.precision, at.scale)
    if pa.types.is_date(at):
        return T.DATE
    if pa.types.is_timestamp(at):
        return T.TIMESTAMP
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return T.VARCHAR
    raise NotImplementedError(f"no engine mapping for arrow type {at}")


def arrow_column_to_payload(arr, t: T.DataType):
    """Arrow chunked array -> engine staging payload."""
    import pyarrow as pa

    combined = arr.combine_chunks()
    nulls = combined.null_count > 0
    if t.is_string:
        ids, valid, dictionary = _encode_arrow_strings(combined)
        if nulls:
            return MaskedColumn(
                data=ids, valid=valid, values=tuple(dictionary)
            )
        return DictColumn(
            ids=ids, values=np.asarray(dictionary, dtype=object)
        )
    if t.is_decimal:
        # arrow decimal128 -> unscaled int64 (short) or (n, 2) int128
        # limb pairs (long), read STRAIGHT from arrow's 16-byte
        # little-endian buffer (measured ~300x over per-value as_py)
        n = len(combined)
        raw = np.frombuffer(
            combined.buffers()[1],
            dtype=np.uint64,
            count=2 * n,
            offset=combined.offset * 16,
        ).reshape(-1, 2)
        lo = raw[:, 0].view(np.int64).copy()
        if t.is_long_decimal:
            hi = raw[:, 1].view(np.int64).copy()
            data = np.stack([hi, lo], axis=1)
        else:
            data = lo
        valid = (
            np.asarray(combined.is_valid(), dtype=bool) if nulls else None
        )
        if valid is not None:
            # zero null slots FIRST: they carry uninitialized bytes
            # that would poison the rescale below (and pages must stay
            # deterministic; masked rows are never observed)
            data[~valid] = 0
        # schema evolution: a file may store the column at a different
        # scale than the table schema (hive derives the schema from its
        # first file). Rounding on downscale is HALF-UP, matching
        # Block.from_pylist ingest (the replaced as_py path truncated
        # toward zero — a deliberate change, codified in
        # tests/test_hive.py::test_decimal_scale_evolution_across_files)
        file_scale = combined.type.scale
        if file_scale != t.scale:
            data = _rescale_unscaled(data, file_scale, t.scale, t)
        if valid is not None:  # reuse the mask computed above
            return MaskedColumn(data=data, valid=valid)
        return data
    elif t.name == "date":
        data = np.asarray(
            combined.cast(pa.int32()).fill_null(0), dtype=np.int64
        )
    elif t.name == "timestamp":
        data = np.asarray(
            combined.cast(pa.int64()).fill_null(0), dtype=np.int64
        )
    elif t.name == "boolean":
        # fill_null(0) would try pa.scalar(0, bool) and fail — the
        # fill value must be a python bool for boolean arrays
        data = np.asarray(combined.fill_null(False), dtype=t.np_dtype)
    else:
        data = np.asarray(
            combined.fill_null(0), dtype=t.np_dtype
        )
    if not nulls:
        return data
    valid = np.asarray(combined.is_valid(), dtype=bool)
    return MaskedColumn(data=data, valid=valid)


def _rescale_unscaled(data, from_scale: int, to_scale: int, t):
    """Exact rescale of unscaled decimal ints (half-up on downscale,
    matching Block.from_pylist's ingest rounding)."""
    if t.is_long_decimal:
        # python-int path: exactness over speed for the rare
        # schema-evolution case
        vals = [T.int128_value(h, l) for h, l in data]
        if to_scale > from_scale:
            vals = [v * 10 ** (to_scale - from_scale) for v in vals]
        else:
            f = 10 ** (from_scale - to_scale)
            vals = [
                (abs(v) + f // 2) // f * (1 if v >= 0 else -1)
                for v in vals
            ]
        return T.int128_limbs(vals)
    if to_scale > from_scale:
        factor = 10 ** (to_scale - from_scale)
        if len(data) and int(
            np.abs(data).max()
        ) > (2 ** 63 - 1) // factor:
            # loud like the old python-int path, not a silent wrap
            raise OverflowError(
                f"decimal rescale x{factor} overflows int64 "
                f"(declared {t}, file scale {from_scale})"
            )
        return data * np.int64(factor)
    f = np.int64(10 ** (from_scale - to_scale))
    q = (np.abs(data) + f // 2) // f
    return np.sign(data) * q


def _encode_arrow_strings(combined):
    """Arrow string column -> (int32 ids, valid, sorted dictionary)."""
    valid = np.asarray(combined.is_valid(), dtype=bool)
    values = combined.fill_null("").to_numpy(zero_copy_only=False)
    values = values.astype(object)
    present = values[valid].astype(str)
    uniq = np.unique(present) if len(present) else np.empty(0, object)
    ids = np.zeros(len(values), dtype=np.int32)
    if len(present):
        ids[valid] = np.searchsorted(
            uniq.astype(str), present
        ).astype(np.int32)
    return ids, valid, uniq.astype(object)
