"""Connector SPI + built-in connectors.

Reference parity: ``presto-spi`` / ``presto-common`` plugin contract —
``ConnectorFactory``, ``ConnectorMetadata``, ``ConnectorSplitManager``,
``ConnectorPageSourceProvider`` (SURVEY.md §2.2). This boundary is the
gate BASELINE.json says to preserve: the engine sees only the SPI;
connectors own table metadata, split enumeration, and page production.

Built-ins (mirroring the reference's test/bench fixtures):
- ``tpch``      — deterministic TPC-H data generated on the fly from the
                  scale factor (SURVEY.md §2.2 presto-tpch)
- ``memory``    — writable in-memory tables (presto-memory)
- ``blackhole`` — null source/sink with configurable fake rows
                  (presto-blackhole, for scheduler/perf tests)
- ``system``    — runtime introspection catalog (presto-system),
                  registered by the server runtime
"""

from presto_tpu.connectors.spi import (  # noqa: F401
    Connector,
    ConnectorMetadata,
    ConnectorSplit,
    SplitSource,
    TableHandle,
)
from presto_tpu.connectors.tpch import TpchConnector  # noqa: F401
from presto_tpu.connectors.tpcds import TpcdsConnector  # noqa: F401
from presto_tpu.connectors.memory import MemoryConnector  # noqa: F401
from presto_tpu.connectors.blackhole import BlackholeConnector  # noqa: F401


def _parquet_factory(**config):
    from presto_tpu.connectors.parquet import ParquetConnector

    return ParquetConnector(**config)


def _orc_factory(**config):
    from presto_tpu.connectors.orc import OrcConnector

    return OrcConnector(**config)


def _hive_factory(**config):
    from presto_tpu.connectors.hive import HiveConnector

    return HiveConnector(**config)


CONNECTOR_FACTORIES = {
    "tpch": TpchConnector,
    "tpcds": TpcdsConnector,
    "memory": MemoryConnector,
    "blackhole": BlackholeConnector,
    "parquet": _parquet_factory,  # lazy: pyarrow imports on first use
    "orc": _orc_factory,
    "hive": _hive_factory,
}


def create_connector(name: str, **config) -> Connector:
    """The ConnectorFactory seam (``connector.name=`` in catalog config)."""
    if name not in CONNECTOR_FACTORIES:
        raise KeyError(f"unknown connector: {name}")
    return CONNECTOR_FACTORIES[name](**config)
