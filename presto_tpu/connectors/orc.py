"""ORC connector: stripe-organized columnar files as queryable tables.

Reference parity: ``presto-orc`` (SURVEY.md §2.2 L9 "file-format
readers") — column-pruned reads with stripe-aligned splits, the second
of the two columnar formats the reference treats as first-class. The
engine-facing contract is identical to the parquet connector's: splits
are row ranges, payloads are device-ready numpy columns
(``connectors/_arrow.py``), so everything above the SPI is
format-agnostic.

TPU-first shape: like parquet, strings leave the reader already
dictionary-encoded and numerics in native representation; the device
only ever sees fixed-width arrays.

Layout: ``root/<schema>/<table>.orc``.

Implementation notes: pyarrow's ORC reader exposes stripe count but not
per-stripe row counts, so stripe row offsets are probed once per file
by reading the narrowest column of each stripe (cheap: one column,
decoded once, then cached). File-footer column statistics are not
exposed by pyarrow's ORC bindings at all, so ``get_table_stats``
returns the row count only — the optimizer falls back to its default
selectivities, exactly as it does for any stats-less connector.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from presto_tpu import types as T
from presto_tpu.connectors._arrow import (
    arrow_column_to_payload,
    arrow_to_engine_type,
)
from presto_tpu.connectors.spi import (
    Connector,
    ConnectorMetadata,
    ConnectorSplit,
    SplitSource,
    TableHandle,
    TableStats,
)
from presto_tpu.server.manifests import LakehouseConnectorMixin


class _OrcMetadata(ConnectorMetadata):
    def __init__(self, conn: "OrcConnector"):
        self._conn = conn

    def list_schemas(self) -> List[str]:
        root = self._conn.root
        out = set(self._conn.lake_list_schemas())
        try:
            out.update(
                d
                for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d))
            )
        except OSError:
            pass
        return sorted(out)

    def list_tables(self, schema: str) -> List[str]:
        d = os.path.join(self._conn.root, schema)
        out = set(self._conn.lake_list_tables(schema))
        try:
            out.update(
                fn[: -len(".orc")]
                for fn in os.listdir(d)
                if fn.endswith(".orc")
            )
        except OSError:
            pass
        return sorted(out)

    def get_table_schema(self, handle: TableHandle) -> Dict[str, T.DataType]:
        lake = self._conn.lake_schema(handle)
        if lake is not None:
            return lake
        f = self._conn._file(handle)
        schema = f.schema
        return {
            schema.field(i).name: arrow_to_engine_type(schema.field(i).type)
            for i in range(len(schema.names))
        }

    def get_table_stats(self, handle: TableHandle) -> TableStats:
        # row count from the ORC footer; pyarrow exposes no per-column
        # min/max for ORC (see module docstring). Manifest-backed
        # tables DO get min/max — the manifest carries them
        lake = self._conn.lake_table_stats(handle)
        if lake is not None:
            return lake
        f = self._conn._file(handle)
        return TableStats(row_count=float(f.nrows), columns={})


class OrcConnector(LakehouseConnectorMixin, Connector):
    """Catalog over ``root/<schema>/<table>.orc`` files, plus
    manifest-backed snapshot tables when ``lakehouse`` is set."""

    def prunes_splits(self) -> bool:
        return True  # per-stripe min/max prune splits

    def __init__(
        self,
        root: str = ".",
        lakehouse: Optional[str] = None,
        catalog: Optional[str] = None,
        target_file_bytes: Optional[int] = None,
        **config,
    ):
        self.root = root
        self._init_lakehouse(
            lakehouse, catalog=catalog,
            target_file_bytes=target_file_bytes,
        )
        self._metadata = _OrcMetadata(self)
        self._files: Dict[TableHandle, object] = {}
        self._offsets: Dict[TableHandle, List[int]] = {}
        #: lazily-probed per-stripe (min, max) of numeric columns —
        #: pyarrow exposes NO ORC column statistics, so the first
        #: range-constrained enumeration reads the column once (the
        #: same probe-and-cache discipline as _stripe_offsets) and
        #: every later query prunes stripes for free
        self._stripe_stats: Dict[tuple, List] = {}

    def metadata(self):
        return self._metadata

    def _path(self, handle: TableHandle) -> str:
        return os.path.join(
            self.root, handle.schema, handle.table + ".orc"
        )

    def _file(self, handle: TableHandle):
        from pyarrow import orc

        f = self._files.get(handle)
        if f is None:
            path = self._path(handle)
            if not os.path.exists(path):
                raise KeyError(f"no ORC table at {path}")
            f = orc.ORCFile(path)
            self._files[handle] = f
        return f

    def _stripe_offsets(self, handle: TableHandle) -> List[int]:
        """Cumulative stripe row offsets ``[0, n0, n0+n1, ...]``, probed
        once by reading each stripe's narrowest column (pyarrow has no
        stripe-row metadata accessor; ``columns=[]`` reads zero rows)."""
        offs = self._offsets.get(handle)
        if offs is None:
            f = self._file(handle)
            probe = _narrowest_column(f.schema)
            offs = [0]
            for i in range(f.nstripes):
                offs.append(
                    offs[-1]
                    + f.read_stripe(i, columns=[probe]).num_rows
                )
            if offs[-1] != f.nrows:  # pragma: no cover - corrupt file
                raise IOError(
                    f"ORC stripe rows {offs[-1]} != footer rows {f.nrows}"
                )
            self._offsets[handle] = offs
        return offs

    def _stripe_minmax(self, handle: TableHandle, col: str):
        """Per-stripe (min, max) of one numeric column, probed once by
        reading just that column per stripe and cached (ORC footers
        carry these stats but pyarrow does not expose them). Entries
        are None when a stripe has no non-null values."""
        key = (handle, col)
        cached = self._stripe_stats.get(key)
        if cached is not None:
            return cached
        import numpy as np

        f = self._file(handle)
        out: List = []
        for i in range(f.nstripes):
            arr = f.read_stripe(i, columns=[col]).column(col)
            vals = arr.to_numpy(zero_copy_only=False)
            vals = vals[~_isnan_or_none(vals)]
            if len(vals) == 0:
                out.append(None)
            else:
                out.append((_pynum(np.min(vals)), _pynum(np.max(vals))))
        self._stripe_stats[key] = out
        return out

    def get_splits(
        self, handle: TableHandle, target_split_rows: int = 1 << 20,
        constraint=(),
    ) -> SplitSource:
        """Stripe-aligned splits (the reference's ORC split boundary),
        expressed as row ranges so the split protocol stays
        format-agnostic. Dynamic-filter :class:`RangeSet` constraints
        on numeric columns prune whole stripes against the (lazily
        probed, cached) per-stripe min/max — excluded stripes are
        never decoded again."""
        from presto_tpu.connectors.spi import RangeSet

        lake = self.lake_splits(handle, target_split_rows, constraint)
        if lake is not None:
            return lake
        offs = self._stripe_offsets(handle)
        total = offs[-1]
        n_stripes = len(offs) - 1
        keep = [True] * n_stripes
        schema = self._metadata.get_table_schema(handle)
        for col, dom in constraint:
            if not isinstance(dom, RangeSet) or n_stripes == 0:
                continue
            t = schema.get(col)
            # plain numeric columns only: dates decode as datetime64
            # and decimals as Decimal objects — neither compares with
            # the RangeSet's native-repr ints (over-retain instead)
            if (
                t is None
                or t.name not in ("bigint", "integer", "double", "real")
                or not isinstance(dom.lo, (int, float))
            ):
                continue
            try:
                stats = self._stripe_minmax(handle, col)
            except Exception:
                continue  # unreadable probe: don't prune on it
            for i, mm in enumerate(stats):
                if mm is None:
                    keep[i] = False  # all-null stripe: no key matches
                elif mm[1] < dom.lo or mm[0] > dom.hi:
                    keep[i] = False
        from presto_tpu.connectors.spi import coalesce_kept_chunks

        chunk_rows = [
            offs[i + 1] - offs[i] for i in range(n_stripes)
        ]
        return SplitSource(
            coalesce_kept_chunks(
                handle, chunk_rows, keep, target_split_rows
            )
        )

    def create_page_source(
        self, split: ConnectorSplit, columns: Sequence[str]
    ) -> Dict[str, object]:
        import pyarrow as pa

        lake = self.lake_page_source(split, columns)
        if lake is not None:
            return lake
        f = self._file(split.table)
        schema = self._metadata.get_table_schema(split.table)
        offs = self._stripe_offsets(split.table)
        # map the row range onto stripes, then TRIM to exactly
        # [row_start, row_end) — workers batch scans at arbitrary
        # boundaries, not just stripe edges
        batches = []
        first_lo = None
        for i in range(len(offs) - 1):
            lo, hi = offs[i], offs[i + 1]
            if lo < split.row_end and hi > split.row_start:
                if first_lo is None:
                    first_lo = lo
                batches.append(f.read_stripe(i, columns=list(columns)))
        if not batches:
            # empty table (0 stripes) or empty range: typed empty arrays
            # (null-typed ones poison arrow_column_to_payload's fill_null)
            arrow_types = {
                f.schema.field(i).name: f.schema.field(i).type
                for i in range(len(f.schema.names))
            }
            table = pa.table(
                {c: pa.array([], type=arrow_types[c]) for c in columns}
            )
            first_lo = split.row_start
        else:
            table = pa.Table.from_batches(batches)
        a = split.row_start - first_lo
        b = split.row_end - first_lo
        table = table.slice(a, b - a)
        out: Dict[str, object] = {}
        for name in columns:
            arr = table.column(name)
            out[name] = arrow_column_to_payload(arr, schema[name])
        return out


def _isnan_or_none(vals):
    """Null mask of a to_numpy'd arrow column (object None / float NaN)."""
    import numpy as np

    if vals.dtype == object:
        return np.asarray([v is None for v in vals], bool)
    if vals.dtype.kind == "f":
        return np.isnan(vals)
    return np.zeros(len(vals), bool)


def _pynum(v):
    """numpy scalar -> exact python number (stats cache entries)."""
    import numpy as np

    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, (np.integer, int)):
        return int(v)
    raise ValueError(f"non-numeric stripe stat {type(v).__name__}")


_WIDTHS = {
    "bool": 1, "int8": 1, "int16": 2, "int32": 4, "float": 4,
    "date32[day]": 4, "int64": 8, "double": 8,
}


def _narrowest_column(schema) -> str:
    """Cheapest column to decode when probing stripe row counts."""
    best, best_w = schema.names[0], 1 << 30
    for i, name in enumerate(schema.names):
        w = _WIDTHS.get(str(schema.field(i).type), 16)
        if w < best_w:
            best, best_w = name, w
    return best
