"""In-memory writable connector (reference: ``presto-memory``,
SURVEY.md §2.2 — the writable test fixture), extended with the
snapshot SPI of the streaming-ingest lane (Iceberg-style snapshot
reads, the declared SPI long-tail of COMPONENTS.md §2.2).

Snapshot model: ingest tables are APPEND-ONLY, so a committed snapshot
is just a prefix length — ``snapshot id -> row count``. Appends build
NEW concatenated column arrays (the old tuple is never mutated), so
rows ``[0, n)`` are identical in every later version and a pinned
reader slicing within its snapshot's row count always sees exactly the
committed prefix, never a torn batch. Snapshot ids are MINTED by the
ingest lane (``server/ingest.py``, where the WAL commit frame makes
them durable — the ``ingest-frames`` analysis rule confines minting
there); this connector only stores and serves them. Tables written
through the legacy INSERT/CTAS/DELETE path never gain snapshots and
behave bit-exactly as before; a destructive write (``replace_rows``,
``drop_table``) on a versioned table drops its snapshot history — the
prefix property no longer holds."""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Dict, Optional, Sequence

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.spi import (
    Connector,
    ConnectorMetadata,
    ConnectorSplit,
    SplitSource,
    TableHandle,
    TableStats,
)


class _MemMetadata(ConnectorMetadata):
    def __init__(self, store):
        self._store = store

    def list_schemas(self):
        return sorted({s for s, _ in self._store.tables})

    def list_tables(self, schema):
        return sorted(t for s, t in self._store.tables if s == schema)

    def get_table_schema(self, handle: TableHandle):
        key = (handle.schema, handle.table)
        if key not in self._store.tables:
            raise KeyError(f"table not found: {handle.schema}.{handle.table}")
        return dict(self._store.tables[key][0])

    def get_table_stats(self, handle: TableHandle):
        key = (handle.schema, handle.table)
        schema, data = self._store.tables[key]
        n = len(next(iter(data.values()))) if data else 0
        # a pinned handle's cardinality is its snapshot's prefix, not
        # the live row count (the optimizer and the ranged scheduler
        # both size work off these stats)
        snap = self._store.snapshots.get(key)
        if handle.snapshot is not None and snap:
            n = min(n, snap.get(handle.snapshot, n))
        return TableStats(row_count=float(n))


class _Store:
    def __init__(self):
        self.tables: Dict[tuple, tuple] = {}  # (schema, table) -> (schema, cols)
        #: (schema, table) -> OrderedDict{snapshot id -> committed row
        #: count}, insertion order = commit order (the last entry is
        #: the tip). Only the ingest lane populates this.
        self.snapshots: Dict[tuple, "OrderedDict[int, int]"] = {}


class MemoryConnector(Connector):
    def __init__(self, **config):
        self._store = _Store()
        self._metadata = _MemMetadata(self._store)
        # serializes snapshot registration against pinning (appends
        # themselves are atomic tuple swaps under the GIL)
        self._snap_mu = threading.Lock()

    def metadata(self):
        return self._metadata

    def supports_writes(self):
        return True

    def create_table(self, handle: TableHandle, schema: Dict[str, T.DataType]):
        # recreation is destructive like replace_rows/drop_table: any
        # snapshot history of the old incarnation must not resolve
        # against the new table's unrelated rows
        with self._snap_mu:
            self._store.snapshots.pop((handle.schema, handle.table), None)
        # empty columns from the start: a never-inserted table must
        # still scan (zero rows), e.g. NOT IN (SELECT ... FROM empty)
        self._store.tables[(handle.schema, handle.table)] = (
            dict(schema),
            {c: np.empty(0, dtype=object) for c in schema},
        )

    def append_rows(self, handle: TableHandle, data: Dict[str, np.ndarray]):
        key = (handle.schema, handle.table)
        schema, existing = self._store.tables[key]
        from presto_tpu.exec.staging import obj_array

        merged = {}
        for col in schema:
            new = obj_array(data[col])
            merged[col] = (
                np.concatenate([existing[col], new]) if existing else new
            )
        self._store.tables[key] = (schema, merged)

    def drop_table(self, handle: TableHandle) -> bool:
        with self._snap_mu:
            self._store.snapshots.pop((handle.schema, handle.table), None)
        return (
            self._store.tables.pop(
                (handle.schema, handle.table), None
            )
            is not None
        )

    def replace_rows(
        self, handle: TableHandle, data: Dict[str, np.ndarray]
    ):
        """Overwrite the table's contents (the DELETE path keeps the
        complement and replaces wholesale). Destroys the append-only
        prefix property, so any snapshot history is dropped — the
        table degrades to legacy unversioned reads."""
        key = (handle.schema, handle.table)
        schema, _ = self._store.tables[key]
        from presto_tpu.exec.staging import obj_array

        with self._snap_mu:
            self._store.snapshots.pop(key, None)
        self._store.tables[key] = (
            schema,
            {c: obj_array(data[c]) for c in schema},
        )

    # ------------------------------------------------------ snapshot SPI

    def commit_snapshot(
        self,
        handle: TableHandle,
        data: Dict[str, np.ndarray],
        snapshot_id: int,
    ) -> int:
        """Fold one committed ingest delta into the table and register
        ``snapshot_id`` at the resulting row count. The id is minted by
        the ingest lane (its WAL commit frame is the durability point);
        ids must arrive in increasing order per table. Returns the
        snapshot's row count."""
        key = (handle.schema, handle.table)
        if any(len(v) for v in data.values()):
            self.append_rows(handle, data)
        _schema, cols = self._store.tables[key]
        n = len(next(iter(cols.values()))) if cols else 0
        with self._snap_mu:
            self._store.snapshots.setdefault(key, OrderedDict())[
                int(snapshot_id)
            ] = n
        return n

    def restore_snapshots(
        self, handle: TableHandle, pairs
    ) -> None:
        """Re-register historical ``(snapshot id, row count)`` pairs
        recovered from a durable manifest chain (restart restore) —
        time travel over the append-only prefix survives the process.
        Counts are clamped to the live rows; ids merge in ascending
        order with whatever the restore already committed."""
        key = (handle.schema, handle.table)
        entry = self._store.tables.get(key)
        if entry is None:
            return
        _schema, cols = entry
        live = len(next(iter(cols.values()))) if cols else 0
        with self._snap_mu:
            snaps = self._store.snapshots.setdefault(
                key, OrderedDict()
            )
            merged = dict(snaps)
            for sid, n in pairs:
                merged[int(sid)] = min(int(n), live)
            snaps.clear()
            for sid in sorted(merged):
                snaps[sid] = merged[sid]

    def current_snapshot_id(self, handle: TableHandle) -> Optional[int]:
        with self._snap_mu:
            snaps = self._store.snapshots.get(
                (handle.schema, handle.table)
            )
            if not snaps:
                return None
            return next(reversed(snaps))

    def pin_snapshot(self, handle: TableHandle) -> TableHandle:
        """Pin the tip snapshot when the table is versioned AND the tip
        still covers the live contents. A table that also took legacy
        (unversioned) appends since its last commit serves unpinned —
        legacy writes keep their read-your-writes semantics, and
        isolation resumes at the next ingest commit."""
        key = (handle.schema, handle.table)
        if handle.snapshot is not None:
            # an EXPLICIT pin (FOR VERSION AS OF) must resolve to a
            # committed snapshot — an unknown id would silently serve
            # the live table as if it were history
            with self._snap_mu:
                snaps = self._store.snapshots.get(key)
                if snaps is None or handle.snapshot not in snaps:
                    raise KeyError(
                        f"snapshot {handle.snapshot} is not available "
                        f"for {handle.schema}.{handle.table}"
                    )
            return handle
        with self._snap_mu:
            snaps = self._store.snapshots.get(key)
            if not snaps:
                return handle
            sid = next(reversed(snaps))
            tip_rows = snaps[sid]
        entry = self._store.tables.get(key)
        if entry is None:
            return handle
        _schema, cols = entry
        live = len(next(iter(cols.values()))) if cols else 0
        if live != tip_rows:
            return handle
        return dataclasses.replace(handle, snapshot=sid)

    def _visible_rows(self, handle: TableHandle, live: int) -> int:
        """Row count a handle may see: its pinned snapshot's prefix, or
        the live count when unpinned (or the pinned id is unknown — a
        destructive write cleared the history; serving live is the
        documented degradation, never an error)."""
        if handle.snapshot is None:
            return live
        with self._snap_mu:
            snaps = self._store.snapshots.get(
                (handle.schema, handle.table)
            )
            if snaps is None:
                return live
            return min(live, snaps.get(handle.snapshot, live))

    def get_splits(self, handle: TableHandle, target_split_rows: int = 1 << 20, constraint=()):
        schema, data = self._store.tables[(handle.schema, handle.table)]
        n = len(next(iter(data.values()))) if data else 0
        n = self._visible_rows(handle, n)
        # splits carry the (possibly pinned) handle, so page sources
        # and the staged-page cache key on the exact version they read
        splits = [
            ConnectorSplit(handle, lo, min(lo + target_split_rows, n))
            for lo in range(0, n, target_split_rows)
        ] or [ConnectorSplit(handle, 0, 0)]
        return SplitSource(splits)

    def create_page_source(self, split: ConnectorSplit, columns: Sequence[str]):
        schema, data = self._store.tables[
            (split.table.schema, split.table.table)
        ]
        # clamp to the pinned snapshot's prefix: a split minted before
        # a commit must not widen into rows appended after its pin
        n = len(next(iter(data.values()))) if data else 0
        hi = min(split.row_end, self._visible_rows(split.table, n))
        return {c: data[c][split.row_start : hi] for c in columns}
