"""In-memory writable connector (reference: ``presto-memory``,
SURVEY.md §2.2 — the writable test fixture)."""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.spi import (
    Connector,
    ConnectorMetadata,
    ConnectorSplit,
    SplitSource,
    TableHandle,
    TableStats,
)


class _MemMetadata(ConnectorMetadata):
    def __init__(self, store):
        self._store = store

    def list_schemas(self):
        return sorted({s for s, _ in self._store.tables})

    def list_tables(self, schema):
        return sorted(t for s, t in self._store.tables if s == schema)

    def get_table_schema(self, handle: TableHandle):
        key = (handle.schema, handle.table)
        if key not in self._store.tables:
            raise KeyError(f"table not found: {handle.schema}.{handle.table}")
        return dict(self._store.tables[key][0])

    def get_table_stats(self, handle: TableHandle):
        key = (handle.schema, handle.table)
        schema, data = self._store.tables[key]
        n = len(next(iter(data.values()))) if data else 0
        return TableStats(row_count=float(n))


class _Store:
    def __init__(self):
        self.tables: Dict[tuple, tuple] = {}  # (schema, table) -> (schema, cols)


class MemoryConnector(Connector):
    def __init__(self, **config):
        self._store = _Store()
        self._metadata = _MemMetadata(self._store)

    def metadata(self):
        return self._metadata

    def supports_writes(self):
        return True

    def create_table(self, handle: TableHandle, schema: Dict[str, T.DataType]):
        # empty columns from the start: a never-inserted table must
        # still scan (zero rows), e.g. NOT IN (SELECT ... FROM empty)
        self._store.tables[(handle.schema, handle.table)] = (
            dict(schema),
            {c: np.empty(0, dtype=object) for c in schema},
        )

    def append_rows(self, handle: TableHandle, data: Dict[str, np.ndarray]):
        key = (handle.schema, handle.table)
        schema, existing = self._store.tables[key]
        from presto_tpu.exec.staging import obj_array

        merged = {}
        for col in schema:
            new = obj_array(data[col])
            merged[col] = (
                np.concatenate([existing[col], new]) if existing else new
            )
        self._store.tables[key] = (schema, merged)

    def drop_table(self, handle: TableHandle) -> bool:
        return (
            self._store.tables.pop(
                (handle.schema, handle.table), None
            )
            is not None
        )

    def replace_rows(
        self, handle: TableHandle, data: Dict[str, np.ndarray]
    ):
        """Overwrite the table's contents (the DELETE path keeps the
        complement and replaces wholesale)."""
        key = (handle.schema, handle.table)
        schema, _ = self._store.tables[key]
        from presto_tpu.exec.staging import obj_array

        self._store.tables[key] = (
            schema,
            {c: obj_array(data[c]) for c in schema},
        )

    def get_splits(self, handle: TableHandle, target_split_rows: int = 1 << 20, constraint=()):
        schema, data = self._store.tables[(handle.schema, handle.table)]
        n = len(next(iter(data.values()))) if data else 0
        splits = [
            ConnectorSplit(handle, lo, min(lo + target_split_rows, n))
            for lo in range(0, n, target_split_rows)
        ] or [ConnectorSplit(handle, 0, 0)]
        return SplitSource(splits)

    def create_page_source(self, split: ConnectorSplit, columns: Sequence[str]):
        schema, data = self._store.tables[
            (split.table.schema, split.table.table)
        ]
        return {c: data[c][split.row_start : split.row_end] for c in columns}
