"""TPC-H connector: deterministic data generated on the fly.

Reference parity: ``presto-tpch`` — data derived from the scale factor at
scan time, zero stored bytes, so every correctness suite can assert exact
results (SURVEY.md §2.2, §4.4). Schemas ``tiny`` (SF0.01), ``sf1``,
``sf10``, ``sf100`` like the reference.

TPU-first redesign of dbgen: every column is a *closed-form function of
the row index* — splitmix64 streams for values, arithmetic bijections for
key relationships (lineitem row -> (order, linenumber) in O(1) via the
7-line cycle closed form). This makes any split [row_start, row_end)
generatable independently, vectorized in numpy, with no sequential RNG
state (the property the reference gets from per-split dbgen seeds).
Varchar columns emit dictionary ids + the (sorted) dictionary directly —
strings never materialise per row, which makes scan staging pure numeric
work (SURVEY.md §7 "Strings on TPU").

Distributions are TPC-H-shaped (official ranges, FK validity, the
partsupp supplier formula, Q-relevant patterns like 'special requests'
comments and BRASS part types) but not bit-identical to dbgen: the
verifier (presto_tpu.verifier) asserts correctness against a CPU oracle
over the SAME generated data, per BASELINE.md's measurement protocol.
"""

from __future__ import annotations

import dataclasses
import datetime
from typing import Dict, List, Optional, Sequence

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.spi import (
    ColumnStats,
    Connector,
    ConnectorMetadata,
    ConnectorSplit,
    SplitSource,
    TableHandle,
    TableStats,
)


@dataclasses.dataclass
class DictColumn:
    """Pre-encoded varchar column: int32 ids into a sorted dictionary."""

    ids: np.ndarray  # int32
    values: np.ndarray  # sorted unique strings


SCHEMAS = {"tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0}

_EPOCH = datetime.date(1970, 1, 1)


def _day(y, m, d):
    return (datetime.date(y, m, d) - _EPOCH).days


STARTDATE = _day(1992, 1, 1)
ENDDATE = _day(1998, 8, 2)
CURRENTDATE = _day(1995, 6, 17)

# ---------------------------------------------------------- random streams

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)


def _mix(x: np.ndarray) -> np.ndarray:
    x = (x ^ (x >> np.uint64(30))) * _M1
    x = (x ^ (x >> np.uint64(27))) * _M2
    return x ^ (x >> np.uint64(31))


def _stream(tag: int, idx: np.ndarray) -> np.ndarray:
    """Deterministic uint64 stream keyed by (column tag, row index)."""
    tag_key = (tag * 0xD1B54A32D192ED03 + 0x632BE59BD9B4E019) % (1 << 64)
    return _mix(
        idx.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
        ^ np.uint64(tag_key)
    )


def _uniform(tag: int, idx: np.ndarray, lo: int, hi: int) -> np.ndarray:
    """Uniform integers in [lo, hi] (inclusive). Large affine index
    ranges route through the native fused loop (native/genstream.cpp,
    bit-exact, measured in tools/bench_native.py); everything else (and
    any host without a toolchain) takes the vectorized numpy path."""
    from presto_tpu import native

    out = native.gen_uniform_native(tag, idx, lo, hi)
    if out is not None:
        return out
    span = (_stream(tag, idx) % np.uint64(hi - lo + 1)).astype(np.int64)
    return lo + span


# ---------------------------------------------------------- word material

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
INSTRUCTIONS = [
    "COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN",
]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished",
    "chartreuse", "chiffon", "chocolate", "coral", "cornflower",
    "cornsilk", "cream", "cyan", "dark", "deep", "dim", "dodger", "drab",
    "firebrick", "floral", "forest", "frosted", "gainsboro", "ghost",
    "goldenrod", "green", "grey", "honeydew", "hot", "indian", "ivory",
    "khaki", "lace", "lavender", "lawn", "lemon", "light", "lime",
    "linen", "magenta", "maroon", "medium", "metallic", "midnight",
    "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange",
    "orchid", "pale", "papaya", "peach", "peru", "pink", "plum", "powder",
    "puff", "purple", "red", "rose", "rosy", "royal", "saddle", "salmon",
    "sandy", "seashell", "sienna", "sky", "slate", "smoke", "snow",
    "spring", "steel", "tan", "thistle", "tomato", "turquoise", "violet",
    "wheat", "white", "yellow",
]
# comment vocabulary: Q13 greps '%special%requests%', Q16 greps
# '%Customer%Complaints%' — both reachable by construction
COMMENT_W1 = [
    "carefully", "quickly", "furiously", "slyly", "blithely", "special",
    "express", "regular", "final", "pending", "ironic", "bold", "even",
    "silent", "unusual", "Customer",
]
COMMENT_W2 = [
    "packages", "deposits", "requests", "accounts", "instructions",
    "foxes", "pinto beans", "theodolites", "dependencies", "excuses",
    "platelets", "ideas", "Complaints", "asymptotes", "dugouts",
    "sheaves",
]
COMMENT_W3 = [
    "sleep", "haggle", "nag", "wake", "cajole", "detect", "integrate",
    "use", "boost", "doze", "engage", "affix", "dazzle", "snooze",
    "breach", "unwind",
]


def _combo_dictionary(*lists: Sequence[str]):
    """All cross-product phrases, sorted; plus the rank lookup table
    mapping raw combo index -> sorted dictionary id."""
    phrases = []
    for a in lists[0]:
        if len(lists) == 1:
            phrases.append(a)
            continue
        for b in lists[1]:
            if len(lists) == 2:
                phrases.append(f"{a} {b}")
            else:
                for c in lists[2]:
                    phrases.append(f"{a} {b} {c}")
    arr = np.asarray(phrases, dtype=object)
    order = np.argsort(arr.astype(str), kind="stable")
    rank = np.empty(len(arr), dtype=np.int32)
    rank[order] = np.arange(len(arr), dtype=np.int32)
    return arr[order], rank


class _LazyCombo:
    """Combo dictionary built once on first use (hundreds of kB)."""

    def __init__(self, *lists):
        self.lists = lists
        self._built = None

    def get(self):
        if self._built is None:
            self._built = _combo_dictionary(*self.lists)
        return self._built

    def column(self, tag: int, idx: np.ndarray) -> DictColumn:
        values, rank = self.get()
        sizes = [len(l) for l in self.lists]
        total = int(np.prod(sizes))
        raw = (_stream(tag, idx) % np.uint64(total)).astype(np.int64)
        return DictColumn(ids=rank[raw], values=values)


_COMMENTS = _LazyCombo(COMMENT_W1, COMMENT_W2, COMMENT_W3)
_P_NAME = _LazyCombo(COLORS, COLORS)
_P_TYPE = _LazyCombo(TYPE_S1, TYPE_S2, TYPE_S3)
_CONTAINERS = _LazyCombo(CONTAINER_S1, CONTAINER_S2)


def _numbered(prefix: str, count: int, keys: np.ndarray) -> DictColumn:
    """'Customer#000000001'-style names: zero-padded => sorted order is
    numeric order, so ids are just key-1 (no string materialisation for
    the ids; the dictionary itself is built lazily by the page builder)."""
    values = np.asarray(
        [f"{prefix}#{i + 1:09d}" for i in range(count)], dtype=object
    )
    return DictColumn(ids=(keys - 1).astype(np.int32), values=values)


def _fixed(values: Sequence[str], picks: np.ndarray) -> DictColumn:
    arr = np.asarray(values, dtype=object)
    order = np.argsort(arr.astype(str), kind="stable")
    rank = np.empty(len(arr), dtype=np.int32)
    rank[order] = np.arange(len(arr), dtype=np.int32)
    return DictColumn(ids=rank[picks.astype(np.int64)], values=arr[order])


# ------------------------------------------------------------- row counts


def _counts(sf: float) -> Dict[str, int]:
    orders = int(1_500_000 * sf)
    return {
        "region": 5,
        "nation": 25,
        "supplier": max(int(10_000 * sf), 1),
        "customer": max(int(150_000 * sf), 1),
        "part": max(int(200_000 * sf), 1),
        "partsupp": max(int(200_000 * sf), 1) * 4,
        "orders": max(orders, 1),
        "lineitem": _lineitem_count(max(orders, 1)),
    }


def _lineitem_count(n_orders: int) -> int:
    """Lines per order cycle 1..7 => closed form."""
    full, rem = divmod(n_orders, 7)
    return full * 28 + rem * (rem + 1) // 2


_CYCLE_BOUNDS = np.array([0, 1, 3, 6, 10, 15, 21, 28], dtype=np.int64)


def _lineitem_order(rows: np.ndarray):
    """Global lineitem row -> (order index 0-based, linenumber 1-based)."""
    cyc, rr = np.divmod(rows, 28)
    j = np.searchsorted(_CYCLE_BOUNDS, rr, side="right") - 1
    order_idx = cyc * 7 + j
    linenumber = rr - _CYCLE_BOUNDS[j] + 1
    return order_idx, linenumber


# --------------------------------------------------------------- schemas

D12_2 = T.decimal(12, 2)

TABLE_SCHEMAS: Dict[str, Dict[str, T.DataType]] = {
    "region": {
        "r_regionkey": T.INTEGER,
        "r_name": T.VARCHAR,
        "r_comment": T.VARCHAR,
    },
    "nation": {
        "n_nationkey": T.INTEGER,
        "n_name": T.VARCHAR,
        "n_regionkey": T.INTEGER,
        "n_comment": T.VARCHAR,
    },
    "supplier": {
        "s_suppkey": T.INTEGER,
        "s_name": T.VARCHAR,
        "s_address": T.VARCHAR,
        "s_nationkey": T.INTEGER,
        "s_phone": T.VARCHAR,
        "s_acctbal": D12_2,
        "s_comment": T.VARCHAR,
    },
    "customer": {
        "c_custkey": T.INTEGER,
        "c_name": T.VARCHAR,
        "c_address": T.VARCHAR,
        "c_nationkey": T.INTEGER,
        "c_phone": T.VARCHAR,
        "c_acctbal": D12_2,
        "c_mktsegment": T.VARCHAR,
        "c_comment": T.VARCHAR,
    },
    "part": {
        "p_partkey": T.INTEGER,
        "p_name": T.VARCHAR,
        "p_mfgr": T.VARCHAR,
        "p_brand": T.VARCHAR,
        "p_type": T.VARCHAR,
        "p_size": T.INTEGER,
        "p_container": T.VARCHAR,
        "p_retailprice": D12_2,
        "p_comment": T.VARCHAR,
    },
    "partsupp": {
        "ps_partkey": T.INTEGER,
        "ps_suppkey": T.INTEGER,
        "ps_availqty": T.INTEGER,
        "ps_supplycost": D12_2,
        "ps_comment": T.VARCHAR,
    },
    "orders": {
        "o_orderkey": T.INTEGER,
        "o_custkey": T.INTEGER,
        "o_orderstatus": T.VARCHAR,
        "o_totalprice": D12_2,
        "o_orderdate": T.DATE,
        "o_orderpriority": T.VARCHAR,
        "o_clerk": T.VARCHAR,
        "o_shippriority": T.INTEGER,
        "o_comment": T.VARCHAR,
    },
    "lineitem": {
        "l_orderkey": T.INTEGER,
        "l_partkey": T.INTEGER,
        "l_suppkey": T.INTEGER,
        "l_linenumber": T.INTEGER,
        "l_quantity": D12_2,
        "l_extendedprice": D12_2,
        "l_discount": D12_2,
        "l_tax": D12_2,
        "l_returnflag": T.VARCHAR,
        "l_linestatus": T.VARCHAR,
        "l_shipdate": T.DATE,
        "l_commitdate": T.DATE,
        "l_receiptdate": T.DATE,
        "l_shipinstruct": T.VARCHAR,
        "l_shipmode": T.VARCHAR,
        "l_comment": T.VARCHAR,
    },
}

# NOTE: keys are INTEGER (32-bit) rather than the reference's BIGINT — a
# deliberate narrowing (max orderkey at SF100 ≈ 6e8 < 2^31) that keeps
# two-column join keys bijectively packable into int64 (ops.join).


# ------------------------------------------------------------ generators


def _retailprice(partkey: np.ndarray) -> np.ndarray:
    return 90000 + (partkey % 20001) + 100 * (partkey % 1000)  # unscaled c


def _ps_suppkey(partkey: np.ndarray, i: np.ndarray, S: int) -> np.ndarray:
    """The official partsupp supplier spread: 4 distinct suppliers/part."""
    return ((partkey - 1 + i * (S // 4) + (partkey - 1) // S) % S) + 1


class TpchGenerator:
    def __init__(self, sf: float):
        self.sf = sf
        self.counts = _counts(sf)

    def generate(
        self, table: str, lo: int, hi: int, columns: Sequence[str]
    ) -> Dict[str, object]:
        rows = np.arange(lo, hi, dtype=np.int64)
        fn = getattr(self, f"_gen_{table}")
        return fn(rows, list(columns))

    # each generator returns {col: numpy array | DictColumn}

    def _gen_region(self, rows, columns):
        out = {}
        for c in columns:
            if c == "r_regionkey":
                out[c] = rows
            elif c == "r_name":
                out[c] = _fixed(REGIONS, rows % 5)
            elif c == "r_comment":
                out[c] = _COMMENTS.column(101, rows)
        return out

    def _gen_nation(self, rows, columns):
        regionkeys = np.asarray([r for _, r in NATIONS], dtype=np.int64)
        out = {}
        for c in columns:
            if c == "n_nationkey":
                out[c] = rows
            elif c == "n_name":
                out[c] = _fixed([n for n, _ in NATIONS], rows)
            elif c == "n_regionkey":
                out[c] = regionkeys[rows]
            elif c == "n_comment":
                out[c] = _COMMENTS.column(102, rows)
        return out

    def _gen_supplier(self, rows, columns):
        keys = rows + 1
        out = {}
        for c in columns:
            if c == "s_suppkey":
                out[c] = keys
            elif c == "s_name":
                out[c] = _numbered("Supplier", self.counts["supplier"], keys)
            elif c == "s_address":
                out[c] = _COMMENTS.column(201, rows)
            elif c == "s_nationkey":
                out[c] = _uniform(202, rows, 0, 24)
            elif c == "s_phone":
                out[c] = _phone(203, rows, _uniform(202, rows, 0, 24))
            elif c == "s_acctbal":
                out[c] = _uniform(204, rows, -99999, 999999)
            elif c == "s_comment":
                out[c] = _COMMENTS.column(205, rows)
        return out

    def _gen_customer(self, rows, columns):
        keys = rows + 1
        out = {}
        for c in columns:
            if c == "c_custkey":
                out[c] = keys
            elif c == "c_name":
                out[c] = _numbered("Customer", self.counts["customer"], keys)
            elif c == "c_address":
                out[c] = _COMMENTS.column(301, rows)
            elif c == "c_nationkey":
                out[c] = _uniform(302, rows, 0, 24)
            elif c == "c_phone":
                out[c] = _phone(303, rows, _uniform(302, rows, 0, 24))
            elif c == "c_acctbal":
                out[c] = _uniform(304, rows, -99999, 999999)
            elif c == "c_mktsegment":
                out[c] = _fixed(SEGMENTS, _uniform(305, rows, 0, 4))
            elif c == "c_comment":
                out[c] = _COMMENTS.column(306, rows)
        return out

    def _gen_part(self, rows, columns):
        keys = rows + 1
        out = {}
        for c in columns:
            if c == "p_partkey":
                out[c] = keys
            elif c == "p_name":
                out[c] = _P_NAME.column(401, rows)
            elif c == "p_mfgr":
                out[c] = _fixed(
                    [f"Manufacturer#{i}" for i in range(1, 6)],
                    _uniform(402, rows, 0, 4),
                )
            elif c == "p_brand":
                m = _uniform(402, rows, 0, 4) + 1
                n = _uniform(403, rows, 1, 5)
                out[c] = _fixed(
                    [f"Brand#{a}{b}" for a in range(1, 6) for b in range(1, 6)],
                    (m - 1) * 5 + (n - 1),
                )
            elif c == "p_type":
                out[c] = _P_TYPE.column(404, rows)
            elif c == "p_size":
                out[c] = _uniform(405, rows, 1, 50)
            elif c == "p_container":
                out[c] = _CONTAINERS.column(406, rows)
            elif c == "p_retailprice":
                out[c] = _retailprice(keys)
            elif c == "p_comment":
                out[c] = _COMMENTS.column(407, rows)
        return out

    def _gen_partsupp(self, rows, columns):
        partkey = rows // 4 + 1
        i = rows % 4
        S = self.counts["supplier"]
        out = {}
        for c in columns:
            if c == "ps_partkey":
                out[c] = partkey
            elif c == "ps_suppkey":
                out[c] = _ps_suppkey(partkey, i, S)
            elif c == "ps_availqty":
                out[c] = _uniform(501, rows, 1, 9999)
            elif c == "ps_supplycost":
                out[c] = _uniform(502, rows, 100, 100000)
            elif c == "ps_comment":
                out[c] = _COMMENTS.column(503, rows)
        return out

    def _gen_orders(self, rows, columns):
        keys = _orderkey(rows)
        odate = STARTDATE + (
            _stream(601, rows) % np.uint64(ENDDATE - 151 - STARTDATE + 1)
        ).astype(np.int64)
        out = {}
        for c in columns:
            if c == "o_orderkey":
                out[c] = keys
            elif c == "o_custkey":
                out[c] = _uniform(602, rows, 1, self.counts["customer"])
            elif c == "o_orderstatus":
                # derived from line statuses; approximated deterministically
                r = _uniform(603, rows, 0, 9)
                out[c] = _fixed(
                    ["F", "O", "P"], np.where(r < 5, 1, np.where(r < 9, 0, 2))
                )
            elif c == "o_totalprice":
                out[c] = _uniform(604, rows, 90000, 55000000)
            elif c == "o_orderdate":
                out[c] = odate
            elif c == "o_orderpriority":
                out[c] = _fixed(PRIORITIES, _uniform(605, rows, 0, 4))
            elif c == "o_clerk":
                nclerk = max(int(1000 * self.sf), 1)
                out[c] = _numbered(
                    "Clerk", nclerk, _uniform(606, rows, 1, nclerk)
                )
            elif c == "o_shippriority":
                out[c] = np.zeros(len(rows), dtype=np.int64)
            elif c == "o_comment":
                out[c] = _COMMENTS.column(607, rows)
        return out

    def _gen_lineitem(self, rows, columns):
        order_idx, linenumber = _lineitem_order(rows)
        okey = _orderkey(order_idx)
        odate = STARTDATE + (
            _stream(601, order_idx) % np.uint64(ENDDATE - 151 - STARTDATE + 1)
        ).astype(np.int64)
        shipdate = odate + _uniform(701, rows, 1, 121)
        partkey = _uniform(702, rows, 1, self.counts["part"])
        qty = _uniform(703, rows, 1, 50)
        out = {}
        for c in columns:
            if c == "l_orderkey":
                out[c] = okey
            elif c == "l_partkey":
                out[c] = partkey
            elif c == "l_suppkey":
                out[c] = _ps_suppkey(
                    partkey, _uniform(704, rows, 0, 3), self.counts["supplier"]
                )
            elif c == "l_linenumber":
                out[c] = linenumber
            elif c == "l_quantity":
                out[c] = qty * 100  # unscaled decimal(12,2)
            elif c == "l_extendedprice":
                out[c] = qty * _retailprice(partkey)
            elif c == "l_discount":
                out[c] = _uniform(705, rows, 0, 10)  # 0.00..0.10
            elif c == "l_tax":
                out[c] = _uniform(706, rows, 0, 8)
            elif c == "l_returnflag":
                receipt = shipdate + _uniform(708, rows, 1, 30)
                ra = _uniform(709, rows, 0, 1)
                out[c] = _fixed(
                    ["A", "N", "R"],
                    np.where(receipt > CURRENTDATE, 1, np.where(ra == 0, 0, 2)),
                )
            elif c == "l_linestatus":
                out[c] = _fixed(
                    ["F", "O"], (shipdate > CURRENTDATE).astype(np.int64)
                )
            elif c == "l_shipdate":
                out[c] = shipdate
            elif c == "l_commitdate":
                out[c] = odate + _uniform(707, rows, 30, 90)
            elif c == "l_receiptdate":
                out[c] = shipdate + _uniform(708, rows, 1, 30)
            elif c == "l_shipinstruct":
                out[c] = _fixed(INSTRUCTIONS, _uniform(710, rows, 0, 3))
            elif c == "l_shipmode":
                out[c] = _fixed(SHIPMODES, _uniform(711, rows, 0, 6))
            elif c == "l_comment":
                out[c] = _COMMENTS.column(712, rows)
        return out


def _orderkey(order_idx: np.ndarray) -> np.ndarray:
    """Sparse order keys (official: 8 used out of every 32)."""
    blk, off = np.divmod(order_idx, 8)
    return blk * 32 + off + 1


_PHONE_LOCALS = list(range(0, 10000, 101))  # 100 bucketed local parts
_PHONE_VALUES = np.asarray(
    [
        f"{c}-{l // 100:03d}-{l % 100:03d}-{l:04d}"
        for c in range(10, 35)
        for l in _PHONE_LOCALS
    ],
    dtype=object,
)  # already sorted: fixed-width country code, then local ascending


def _phone(tag: int, rows: np.ndarray, nationkey: np.ndarray) -> DictColumn:
    """'NN-NNN-NNN-NNNN' with country code nationkey+10 (Q22 substr
    relies on the leading country code). Dictionary ids computed
    arithmetically — the dictionary layout is (country, local-bucket)
    row-major, which matches lexicographic order by construction."""
    bucket = _uniform(tag, rows, 0, len(_PHONE_LOCALS) - 1)
    ids = (nationkey * len(_PHONE_LOCALS) + bucket).astype(np.int32)
    return DictColumn(ids=ids, values=_PHONE_VALUES)


# -------------------------------------------------------------- connector


class _TpchMetadata(ConnectorMetadata):
    def list_schemas(self):
        return list(SCHEMAS)

    def list_tables(self, schema):
        return list(TABLE_SCHEMAS)

    def get_table_schema(self, handle: TableHandle):
        if handle.schema not in SCHEMAS:
            raise KeyError(f"unknown tpch schema: {handle.schema}")
        if handle.table not in TABLE_SCHEMAS:
            raise KeyError(f"unknown tpch table: {handle.table}")
        return dict(TABLE_SCHEMAS[handle.table])

    PRIMARY_KEYS = {
        "region": ("r_regionkey",),
        "nation": ("n_nationkey",),
        "supplier": ("s_suppkey",),
        "customer": ("c_custkey",),
        "part": ("p_partkey",),
        "partsupp": ("ps_partkey", "ps_suppkey"),
        "orders": ("o_orderkey",),
        "lineitem": ("l_orderkey", "l_linenumber"),
    }

    # foreign keys: column -> referenced table (distinct count source)
    FOREIGN_KEYS = {
        "n_regionkey": "region",
        "s_nationkey": "nation",
        "c_nationkey": "nation",
        "ps_partkey": "part",
        "ps_suppkey": "supplier",
        "o_custkey": "customer",
        "l_orderkey": "orders",
        "l_partkey": "part",
        "l_suppkey": "supplier",
    }

    def get_table_stats(self, handle: TableHandle):
        sf = SCHEMAS[handle.schema]
        counts = _counts(sf)
        n = counts[handle.table]
        pk = self.PRIMARY_KEYS[handle.table]

        def key_max(table: str) -> int:
            # orderkeys are sparse (8 of every 32): domain max != rowcount
            if table == "orders":
                return int(_orderkey(np.asarray([counts["orders"] - 1]))[0])
            return counts[table]

        cols: Dict[str, ColumnStats] = {}
        for name in TABLE_SCHEMAS[handle.table]:
            if len(pk) == 1 and name == pk[0]:
                cols[name] = ColumnStats(
                    distinct_count=n, min_value=1, max_value=key_max(handle.table)
                )
            elif name in self.FOREIGN_KEYS:
                ref_table = self.FOREIGN_KEYS[name]
                ref = counts[ref_table]
                cols[name] = ColumnStats(
                    distinct_count=min(ref, n),
                    min_value=1,
                    max_value=key_max(ref_table),
                )
            elif name == "l_linenumber":
                # closed form: 1..7 lines per order
                cols[name] = ColumnStats(
                    distinct_count=7, min_value=1, max_value=7
                )
        return TableStats(row_count=float(n), columns=cols, primary_key=pk)


class TpchConnector(Connector):
    """Catalog 'tpch': schemas tiny/sf1/sf10/sf100, zero stored bytes."""

    def __init__(self, **config):
        self._metadata = _TpchMetadata()
        self._gens: Dict[str, TpchGenerator] = {}

    def metadata(self):
        return self._metadata

    def _gen(self, schema: str) -> TpchGenerator:
        if schema not in self._gens:
            self._gens[schema] = TpchGenerator(SCHEMAS[schema])
        return self._gens[schema]

    def get_splits(self, handle: TableHandle, target_split_rows: int = 1 << 20, constraint=()):
        n = self._gen(handle.schema).counts[handle.table]
        splits = [
            ConnectorSplit(handle, lo, min(lo + target_split_rows, n))
            for lo in range(0, n, target_split_rows)
        ] or [ConnectorSplit(handle, 0, 0)]
        return SplitSource(splits)

    def create_page_source(self, split: ConnectorSplit, columns):
        return self._gen(split.table.schema).generate(
            split.table.table, split.row_start, split.row_end, columns
        )
