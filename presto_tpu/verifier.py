"""Cross-engine verifier: run the same SQL on this engine and on a CPU
oracle (sqlite), diff the results.

Reference parity: ``presto-verifier`` — replay a query corpus against two
engines and diff (SURVEY.md §4.7): "run the same SQL with tpu_offload
on/off and diff". Here the control engine is sqlite over the SAME
generated TPC-H data; the test engine is presto_tpu. SQL is parsed once
by our parser and re-rendered into sqlite's dialect (date arithmetic via
date(), EXTRACT via strftime, decimals as REAL with tolerance-based
comparison).
"""

from __future__ import annotations

import math
import sqlite3
from typing import Dict, List, Optional

import numpy as np

from presto_tpu.connectors.spi import TableHandle
from presto_tpu.connectors.tpch import DictColumn, TpchConnector
from presto_tpu.sql import ast, parse_statement

_EPOCH_OFFSET = 719163  # days from 0001-01-01 to 1970-01-01 per date.toordinal


def _days_to_iso(days: np.ndarray) -> List[str]:
    import datetime

    epoch = datetime.date(1970, 1, 1)
    return [
        (epoch + datetime.timedelta(days=int(d))).isoformat() for d in days
    ]


def _make_moment_agg(ddof: int, sqrt_out: bool):
    """sqlite aggregate factory for stddev/variance families."""

    class _Agg:
        def __init__(self):
            self.vals = []

        def step(self, v):
            if v is not None:
                self.vals.append(float(v))

        def finalize(self):
            n = len(self.vals)
            if n <= ddof:
                return None
            mean = sum(self.vals) / n
            var = sum((x - mean) ** 2 for x in self.vals) / (n - ddof)
            return var ** 0.5 if sqrt_out else var

    return _Agg


class SqliteOracle:
    """sqlite mirror of a generated-catalog schema (decimals as REAL,
    dates as ISO TEXT) plus the dialect renderer. ``catalog`` selects
    the fixture connector: "tpch" (default) or "tpcds"."""

    def __init__(self, schema: str = "tiny", catalog: str = "tpch"):
        self.conn = sqlite3.connect(":memory:")
        # statistics aggregates sqlite lacks (engine side:
        # functions.py registry) — Welford-free two-pass-safe sums
        for name, ddof in (
            ("stddev_samp", 1), ("stddev", 1), ("stddev_pop", 0),
            ("var_samp", 1), ("variance", 1), ("var_pop", 0),
        ):
            self.conn.create_aggregate(
                name, 1, _make_moment_agg(ddof, name.startswith("std"))
            )
        self.schema = schema
        self.catalog = catalog
        if catalog == "tpch":
            self._connector = TpchConnector()
            from presto_tpu.connectors.tpch import TABLE_SCHEMAS as ts
        elif catalog == "tpcds":
            from presto_tpu.connectors.tpcds import (
                TABLE_SCHEMAS as ts,
                TpcdsConnector,
            )

            self._connector = TpcdsConnector()
        else:
            raise KeyError(f"no oracle fixture for catalog {catalog}")
        self._table_schemas = ts
        self._loaded: set = set()

    def load_table(self, table: str) -> None:
        if table in self._loaded:
            return
        tschema = self._table_schemas[table]
        handle = TableHandle(self.catalog, self.schema, table)
        cols = list(tschema)
        defs = []
        for c in cols:
            t = tschema[c]
            if t.is_string or t.name == "date":
                defs.append(f"{c} TEXT")
            elif t.is_decimal or t.name in ("double", "real"):
                defs.append(f"{c} REAL")
            else:
                defs.append(f"{c} INTEGER")
        self.conn.execute(f"CREATE TABLE {table} ({', '.join(defs)})")
        src = self._connector.get_splits(handle, target_split_rows=1 << 20)
        while not src.exhausted:
            for split in src.next_batch(16):
                data = self._connector.create_page_source(split, cols)
                rows = []
                n = split.num_rows
                decoded = {}
                for c in cols:
                    t = tschema[c]
                    v = data[c]
                    if isinstance(v, DictColumn):
                        decoded[c] = v.values[v.ids]
                    elif t.name == "date":
                        decoded[c] = _days_to_iso(v)
                    elif t.is_decimal:
                        decoded[c] = (
                            np.asarray(v, dtype=np.float64) / (10 ** t.scale)
                        )
                    else:
                        decoded[c] = v
                for i in range(n):
                    rows.append(tuple(decoded[c][i] for c in cols))
                self.conn.executemany(
                    f"INSERT INTO {table} VALUES "
                    f"({', '.join('?' * len(cols))})",
                    [
                        tuple(
                            x.item() if isinstance(x, np.generic) else x
                            for x in row
                        )
                        for row in rows
                    ],
                )
        # surrogate/join-key indexes: at SF1+ sqlite's nested-loop
        # joins over multi-million-row fact tables need them to finish
        # in suite-tolerable time (tiny-scale cost is negligible)
        for c in cols:
            if c.endswith("_sk") or c.endswith("key"):
                self.conn.execute(
                    f"CREATE INDEX idx_{table}_{c} ON {table} ({c})"
                )
        self.conn.commit()
        self._loaded.add(table)

    def execute(self, sql: str) -> List[tuple]:
        from presto_tpu.sql.grouping_sets import desugar_tree

        stmt = parse_statement(sql)
        assert isinstance(stmt, ast.Select)
        # sqlite has no GROUPING SETS: render the same desugared tree
        # the planner executes (an independent execution of identical
        # plain-SQL semantics)
        stmt = desugar_tree(stmt)
        for t in _tables_of(stmt):
            if t in self._table_schemas:
                self.load_table(t)
        rendered = render_sqlite(stmt)
        cur = self.conn.execute(rendered)
        return cur.fetchall()


def _tables_of(node) -> set:
    import dataclasses

    out = set()

    def visit(n):
        if isinstance(n, ast.TableRef):
            out.add(n.parts[-1])
        if dataclasses.is_dataclass(n):
            for f in dataclasses.fields(n):
                v = getattr(n, f.name)
                if isinstance(v, ast.Node):
                    visit(v)
                elif isinstance(v, tuple):
                    for x in v:
                        if isinstance(x, ast.Node):
                            visit(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, ast.Node):
                                    visit(y)

    visit(node)
    return out


# ------------------------------------------------------- dialect renderer


def render_sqlite(n: ast.Node) -> str:
    return _r(n)


def _r(n: ast.Node) -> str:
    if isinstance(n, ast.Select):
        parts = []
        if n.ctes:
            parts.append(
                "WITH "
                + ", ".join(f"{name} AS ({_r(q)})" for name, q in n.ctes)
            )
        sel = "SELECT " + ("DISTINCT " if n.distinct else "")
        sel += ", ".join(
            _r(i.expr) + (f" AS {i.alias}" if i.alias else "")
            for i in n.items
        )
        parts.append(sel)
        if n.from_ is not None:
            parts.append("FROM " + _r(n.from_))
        if n.where is not None:
            parts.append("WHERE " + _r(n.where))
        if n.group_by:
            parts.append("GROUP BY " + ", ".join(_r(g) for g in n.group_by))
        if n.having is not None:
            parts.append("HAVING " + _r(n.having))
        if n.order_by:
            parts.append(
                "ORDER BY "
                + ", ".join(
                    _r(s.expr)
                    + (" DESC" if s.descending else "")
                    + (
                        # engine default (ops/sort.py): NULLS LAST in
                        # ASC, FIRST in DESC; sqlite defaults differ,
                        # so render it explicitly either way
                        " NULLS FIRST"
                        if (
                            s.nulls_first
                            if s.nulls_first is not None
                            else s.descending
                        )
                        else " NULLS LAST"
                    )
                    for s in n.order_by
                )
            )
        if n.limit is not None:
            parts.append(f"LIMIT {n.limit}")
        return " ".join(parts)
    if isinstance(n, ast.TableRef):
        t = n.parts[-1]
        return t + (f" AS {n.alias}" if n.alias else "")
    if isinstance(n, ast.SubqueryRef):
        return f"({_r(n.query)}) AS {n.alias}"
    if isinstance(n, ast.JoinRel):
        if n.join_type == "cross":
            return f"{_r(n.left)}, {_r(n.right)}"
        jt = n.join_type.upper()
        on = f" ON {_r(n.on)}" if n.on is not None else ""
        return f"{_r(n.left)} {jt} JOIN {_r(n.right)}{on}"
    if isinstance(n, ast.Ident):
        return ".".join(n.parts)
    if isinstance(n, ast.NumberLit):
        return n.text
    if isinstance(n, ast.StringLit):
        return "'" + n.value.replace("'", "''") + "'"
    if isinstance(n, ast.DateLit):
        return f"'{n.value}'"
    if isinstance(n, ast.NullLit):
        return "NULL"
    if isinstance(n, ast.BoolLit):
        return "1" if n.value else "0"
    if isinstance(n, ast.BinaryOp):
        if n.op in ("+", "-") and isinstance(n.right, ast.IntervalLit):
            sign = "+" if n.op == "+" else "-"
            iv = n.right
            amt = int(iv.value) * (-1 if iv.negative else 1)
            if sign == "-":
                amt = -amt
            return f"date({_r(n.left)}, '{amt:+d} {iv.unit}')"
        if n.op == "%":
            return f"({_r(n.left)} % {_r(n.right)})"
        op = {"and": "AND", "or": "OR"}.get(n.op, n.op)
        return f"({_r(n.left)} {op} {_r(n.right)})"
    if isinstance(n, ast.UnaryOp):
        if n.op == "not":
            return f"(NOT {_r(n.arg)})"
        return f"(-{_r(n.arg)})"
    if isinstance(n, ast.FuncCall):
        if n.window is not None:
            over = []
            if n.window.partition_by:
                over.append(
                    "PARTITION BY "
                    + ", ".join(_r(p) for p in n.window.partition_by)
                )
            if n.window.order_by:
                over.append(
                    "ORDER BY "
                    + ", ".join(
                        _r(s.expr) + (" DESC" if s.descending else "")
                        for s in n.window.order_by
                    )
                )
            if n.window.frame:
                over.append(
                    f"{n.window.frame.upper()} BETWEEN UNBOUNDED "
                    "PRECEDING AND CURRENT ROW"
                )
            args = ", ".join(_r(a) for a in n.args)
            return f"{n.name}({args}) OVER ({' '.join(over)})"
        if n.name == "count" and not n.args:
            return "count(*)"
        if n.name == "substring":
            args = ", ".join(_r(a) for a in n.args)
            return f"substr({args})"
        if n.name == "concat":
            # sqlite <3.44 has no concat(); render the || operator
            return "(" + " || ".join(_r(a) for a in n.args) + ")"
        d = "DISTINCT " if n.distinct else ""
        return f"{n.name}({d}{', '.join(_r(a) for a in n.args)})"
    if isinstance(n, ast.CaseExpr):
        s = "CASE"
        if n.operand is not None:
            s += " " + _r(n.operand)
        for c, v in n.whens:
            s += f" WHEN {_r(c)} THEN {_r(v)}"
        if n.default is not None:
            s += f" ELSE {_r(n.default)}"
        return s + " END"
    if isinstance(n, ast.CastExpr):
        t = n.type_name.lower()
        if t.startswith("decimal") or t in ("double", "real"):
            st = "REAL"
        elif t.startswith("varchar") or t.startswith("char"):
            st = "TEXT"
        else:
            st = "INTEGER"
        return f"CAST({_r(n.arg)} AS {st})"
    if isinstance(n, ast.BetweenExpr):
        neg = "NOT " if n.negate else ""
        return f"({_r(n.arg)} {neg}BETWEEN {_r(n.low)} AND {_r(n.high)})"
    if isinstance(n, ast.InList):
        neg = "NOT " if n.negate else ""
        return (
            f"({_r(n.arg)} {neg}IN "
            f"({', '.join(_r(v) for v in n.values)}))"
        )
    if isinstance(n, ast.InSubquery):
        neg = "NOT " if n.negate else ""
        return f"({_r(n.arg)} {neg}IN ({_r(n.query)}))"
    if isinstance(n, ast.Exists):
        neg = "NOT " if n.negate else ""
        return f"({neg}EXISTS ({_r(n.query)}))"
    if isinstance(n, ast.ScalarSubquery):
        return f"({_r(n.query)})"
    if isinstance(n, ast.LikeExpr):
        neg = "NOT " if n.negate else ""
        return f"({_r(n.arg)} {neg}LIKE {_r(n.pattern)})"
    if isinstance(n, ast.IsNullExpr):
        return f"({_r(n.arg)} IS {'NOT ' if n.negate else ''}NULL)"
    if isinstance(n, ast.ExtractExpr):
        fmt = {"year": "%Y", "month": "%m", "day": "%d"}[n.field.lower()]
        return f"CAST(strftime('{fmt}', {_r(n.arg)}) AS INTEGER)"
    if isinstance(n, ast.Star):
        return (n.qualifier + ".*") if n.qualifier else "*"
    if isinstance(n, ast.ValuesRel):
        # portable rendering: UNION ALL of FROM-less SELECTs (sqlite's
        # VALUES form cannot name columns)
        names = n.column_names or tuple(
            f"_col{i}" for i in range(len(n.rows[0]))
        )
        selects = []
        for ri, row in enumerate(n.rows):
            cols = ", ".join(
                _r(v) + (f" AS {names[ci]}" if ri == 0 else "")
                for ci, v in enumerate(row)
            )
            selects.append("SELECT " + cols)
        return (
            "(" + " UNION ALL ".join(selects) + f") AS {n.alias}"
        )
    if isinstance(n, ast.UnionRel):
        kw = {
            "union_all": "UNION ALL",
            "union": "UNION",
            "intersect": "INTERSECT",
            "except": "EXCEPT",
        }
        rendered = [_r(n.terms[0])]
        for t, op in zip(n.terms[1:], n.ops):
            rendered.append(kw[op])
            rendered.append(_r(t))
        return "(" + " ".join(rendered) + ")"
    if isinstance(n, ast.IntervalLit):
        raise ValueError("bare interval outside date arithmetic")
    raise ValueError(f"cannot render {type(n).__name__} for sqlite")


# ------------------------------------------------------------- comparison


def normalize_row(row, rel_tol=1e-6):
    out = []
    for v in row:
        if isinstance(v, bool):
            out.append(int(v))
        elif isinstance(v, float):
            out.append(v)
        elif hasattr(v, "isoformat"):  # date
            out.append(v.isoformat())
        else:
            out.append(v)
    return tuple(out)


def rows_equal(a, b, rel_tol=1e-6, abs_tol=1e-9) -> bool:
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if x is None or y is None:
            if not (x is None and y is None):
                return False
            continue
        if isinstance(x, float) or isinstance(y, float):
            if not math.isclose(
                float(x), float(y), rel_tol=rel_tol, abs_tol=abs_tol
            ):
                return False
        else:
            if str(x) != str(y):
                return False
    return True


def diff_results(
    ours: List[tuple],
    oracle: List[tuple],
    ordered: bool,
    rel_tol: float = 1e-6,
) -> Optional[str]:
    """None if equal, else a human-readable first-difference report."""
    a = [normalize_row(r, rel_tol) for r in ours]
    b = [normalize_row(r, rel_tol) for r in oracle]
    if not ordered:
        keyf = lambda r: tuple(  # noqa: E731
            (x is None, str(x) if not isinstance(x, float) else round(x, 6))
            for x in r
        )
        a = sorted(a, key=keyf)
        b = sorted(b, key=keyf)
    if len(a) != len(b):
        return f"row count mismatch: engine={len(a)} oracle={len(b)}"
    for i, (ra, rb) in enumerate(zip(a, b)):
        if not rows_equal(ra, rb, rel_tol):
            return f"row {i} differs:\n  engine: {ra}\n  oracle: {rb}"
    return None


def verify_query(
    runner, oracle: SqliteOracle, sql: str, rel_tol: float = 1e-6
) -> Optional[str]:
    """Run on both engines; None = match, else the difference report."""
    ours = runner.execute(sql).rows()
    theirs = oracle.execute(sql)
    stmt = parse_statement(sql)
    ordered = bool(stmt.order_by)
    return diff_results(ours, theirs, ordered, rel_tol)


def verify_offload(sql: str, rel_tol: float = 1e-6) -> Optional[str]:
    """Cross-backend verification: run the SAME SQL on this engine with
    ``tpu_offload`` on and off and diff the results — the reference's
    presto-verifier control-vs-test replay (SURVEY.md §4.7), with the
    backend swap happening at the session gate instead of across
    clusters. On a CPU-only host both runs share a platform (the diff
    still exercises two separately compiled executables); on a TPU host
    this is the TPU-vs-CPU semantic sanitizer."""
    from presto_tpu.exec.local_runner import LocalQueryRunner
    from presto_tpu.session import Session

    on = LocalQueryRunner(
        session=Session(properties={"tpu_offload": True})
    )
    off = LocalQueryRunner(
        session=Session(properties={"tpu_offload": False})
    )
    ours = on.execute(sql).rows()
    theirs = off.execute(sql).rows()
    stmt = parse_statement(sql)
    ordered = bool(stmt.order_by)
    return diff_results(ours, theirs, ordered, rel_tol)
