"""Durable coordinator state: the crash-safe admission journal.

Reference parity: Presto's disaggregated-coordinator direction treats
coordinator state as recoverable — a coordinator bounce must RESUME the
queued/running query set instead of forgetting it (PAPER.md L3; the
spooled exchange of PR 5 already made the data plane restartable, this
journal does the same for the control plane). The journal records, as
they happen:

- query admission (``submit``: qid, SQL, user, resource group, the
  client's prepared-statement map),
- query completion (``finish``: any terminal state — FINISHED, FAILED,
  or RESUMED when a restart re-admitted the query under a new id),
- the coordinator-global prepared-statement registry
  (``prepare`` / ``deallocate``),
- multi-coordinator failover (``claim``: a lease-fenced survivor took
  this journal's open queries over at a fencing epoch; ``alias``: a
  dead peer's qid now resolves to one of OURS — the durable half of
  the cross-coordinator alias chain).

On restart the coordinator replays the journal and re-admits every
query that never reached a terminal state, under the NEW boot's query
ids (the per-boot qid nonce guarantees the re-run's task-attempt ids
can never collide with the dead incarnation's spooled pages); the old
ids stay routable through an alias map so clients paginating across
the bounce reconnect transparently.

On-disk shape (one directory, ``coordinator.journal-path``): JSONL
segment files ``journal-NNNNNN.jsonl`` in the spool's shared-dir style.
Every line is a checksummed frame::

    {crc32-of-payload as 8 hex chars} {payload JSON}

so a torn tail line (crash mid-append) or bit rot is detected at replay
and skipped (``journal.corrupt_lines``) — the journal must always come
back up. Segments rotate after ``segment_lines`` appends; each new
segment opens with a ``checkpoint`` frame carrying the full live state
(open queries + prepared registry, mirroring ``plan/history.py``'s
checkpoint compaction), so GC keeps only the newest two segments and a
long-running coordinator's journal stays bounded by its LIVE state, not
its query count.

Construction and frame parsing are confined to this module
(``tools/check_journal_sites.py`` — an ad-hoc frame writer elsewhere
would silently break replay); the coordinator is the one audited
consumer.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional

from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY

log = logging.getLogger("presto_tpu.journal")

#: appends per segment before rotation (each rotation writes a full
#: checkpoint, so small segments trade write amplification for faster
#: GC; 256 keeps both negligible at query rates)
DEFAULT_SEGMENT_LINES = 256

_SEG_PREFIX = "journal-"
_SEG_SUFFIX = ".jsonl"


def _frame_line(payload: str) -> str:
    """One checksummed journal frame: crc32 of the UTF-8 payload, then
    the payload itself. The crc is verified at replay — a torn write
    truncates the line and fails the check."""
    return f"{zlib.crc32(payload.encode()) & 0xFFFFFFFF:08x} {payload}"


def _parse_line(line: str) -> Optional[dict]:
    """Frame -> record dict, or None for torn/corrupt/foreign lines."""
    line = line.strip()
    if not line:
        return None
    crc_hex, sep, payload = line.partition(" ")
    if not sep or len(crc_hex) != 8:
        return None
    try:
        crc = int(crc_hex, 16)
    except ValueError:
        return None
    if zlib.crc32(payload.encode()) & 0xFFFFFFFF != crc:
        return None
    try:
        rec = json.loads(payload)
    except Exception:
        return None
    return rec if isinstance(rec, dict) and "ev" in rec else None


@dataclasses.dataclass
class JournalState:
    """Live coordinator state reconstructed by replay."""

    #: submit records of queries that never reached a terminal state,
    #: in admission order (qid, sql, user, group, prepared)
    open: List[dict] = dataclasses.field(default_factory=list)
    #: coordinator-global prepared registry: name -> statement text
    prepared: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: dead-incarnation qid -> the OPEN qid its chain of resumes leads
    #: to (collapsed): a client URI from N bounces ago must still
    #: resolve to whatever run carries its query today
    aliases: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: last ``claim`` frame, or None: a peer coordinator fenced this
    #: journal at ``claim["epoch"]`` and took its open queries over. A
    #: restarted original owner must rejoin ABOVE that epoch (the
    #: lease plane reads it at construction) and must not re-admit
    #: what the claimant already resumed — the claimant's RESUMED
    #: close-outs in this same journal guarantee that.
    claim: Optional[dict] = None


class CoordinatorJournal:
    """Append-only admission journal with checkpoint compaction."""

    def __init__(self, path: str, segment_lines: int = DEFAULT_SEGMENT_LINES):
        self.path = path
        self.segment_lines = max(int(segment_lines), 4)
        self._lock = threading.Lock()
        #: qid -> submit record (insertion order = admission order)
        self._open: "OrderedDict[str, dict]" = OrderedDict()
        self._prepared: Dict[str, str] = {}
        #: resumed old qid -> its replacement qid (one hop; collapsed
        #: to the live tip in :meth:`_live_aliases`)
        self._alias: Dict[str, str] = {}
        #: last claim frame a failover survivor fenced this journal at
        self._claim: Optional[dict] = None
        os.makedirs(path, exist_ok=True)
        self._replayed = self._load()

    # ------------------------------------------------------------ disk

    def _segments(self) -> List[str]:
        try:
            names = sorted(
                f
                for f in os.listdir(self.path)
                if f.startswith(_SEG_PREFIX) and f.endswith(_SEG_SUFFIX)
            )
        except OSError:
            return []
        return [os.path.join(self.path, f) for f in names]

    def _cur_segment(self) -> str:
        return os.path.join(
            self.path, f"{_SEG_PREFIX}{self._seg_seq:06d}{_SEG_SUFFIX}"
        )

    def _load(self) -> JournalState:
        """Rebuild live state from surviving segments, oldest first so
        later frames win. Corrupt/torn frames are counted and skipped —
        a journal must always come back up, degraded to whatever
        replayed cleanly."""
        max_seq = -1
        corrupt = 0
        for seg in self._segments():
            name = os.path.basename(seg)
            try:
                max_seq = max(
                    max_seq,
                    int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)]),
                )
            except ValueError:
                pass
            try:
                with open(seg, encoding="utf-8") as f:
                    for raw in f:
                        if not raw.strip():
                            continue
                        rec = _parse_line(raw)
                        if rec is None:
                            corrupt += 1
                            continue
                        self._apply(rec)
            except OSError:
                continue
        if corrupt:
            REGISTRY.counter("journal.corrupt_lines").update(corrupt)
            log.warning(
                "journal replay skipped %d corrupt/torn line(s) under %s",
                corrupt, self.path,
            )
        # numbering continues AFTER the max surviving name (GC leaves
        # gaps; reusing a name would invert replay recency) and a
        # restart always opens a fresh segment
        self._seg_seq = max_seq + 1
        self._cur_count = 0
        state = JournalState(
            open=list(self._open.values()),
            prepared=dict(self._prepared),
            aliases=self._live_aliases(),
            claim=dict(self._claim) if self._claim else None,
        )
        REGISTRY.counter("journal.replayed").update(len(state.open))
        return state

    def _live_aliases(self) -> Dict[str, str]:
        """Alias map collapsed to live tips: every dead-incarnation qid
        whose resume chain ends at a still-OPEN query maps straight to
        that tip; chains ending at a truly finished query are dropped
        (their clients already saw the outcome or never will — nothing
        left to route to)."""
        out: Dict[str, str] = {}
        for a in self._alias:
            tip, seen = a, set()
            while tip in self._alias and tip not in seen:
                seen.add(tip)
                tip = self._alias[tip]
            if tip in self._open:
                out[a] = tip
        return out

    def _apply(self, rec: dict) -> None:
        ev = rec.get("ev")
        if ev == "submit" and rec.get("qid"):
            self._open[rec["qid"]] = rec
        elif ev == "finish":
            self._open.pop(rec.get("qid"), None)
            # a RESUMED close-out names its replacement: the durable
            # half of the restart alias, so a statement URI minted N
            # bounces ago still resolves after bounce N+1
            if rec.get("state") == "RESUMED" and rec.get("resumed_as"):
                self._alias[rec["qid"]] = rec["resumed_as"]
            else:
                self._alias.pop(rec.get("qid"), None)
        elif ev == "alias":
            # cross-coordinator inheritance: a failover survivor folds
            # the DEAD journal's alias chains into its OWN journal, so
            # a statement URI minted two coordinators ago still
            # resolves after the survivor itself dies
            if rec.get("old") and rec.get("qid"):
                self._alias[rec["old"]] = rec["qid"]
        elif ev == "claim":
            self._claim = {
                "claimant": rec.get("claimant", ""),
                "epoch": int(rec.get("epoch", 0)),
            }
        elif ev == "prepare" and rec.get("name"):
            self._prepared[rec["name"]] = rec.get("sql", "")
        elif ev == "deallocate":
            self._prepared.pop(rec.get("name"), None)
        elif ev == "checkpoint":
            # a checkpoint frame is the full state at rotation: reset
            # and re-seed, so older segments become redundant
            self._open = OrderedDict(
                (r.get("qid"), r)
                for r in rec.get("open") or []
                if isinstance(r, dict) and r.get("qid")
            )
            self._prepared = dict(rec.get("prepared") or {})
            self._alias = dict(rec.get("aliases") or {})
            self._claim = (
                dict(rec["claim"]) if rec.get("claim") else None
            )

    # ----------------------------------------------------------- write

    def _append(self, rec: dict) -> None:
        rec.setdefault("ts", time.time())
        line = _frame_line(json.dumps(rec, default=str))
        with self._lock:
            self._apply(rec)
            rotate = self._cur_count >= self.segment_lines
            if rotate:
                self._seg_seq += 1
                self._cur_count = 0
            try:
                faults.maybe_inject_io("write", self._cur_segment())
                with open(self._cur_segment(), "a", encoding="utf-8") as f:
                    if rotate:
                        # checkpoint compaction: the fresh segment
                        # opens with the full live state, so GC can
                        # drop everything older
                        ckpt = {
                            "ev": "checkpoint",
                            "ts": time.time(),
                            "open": list(self._open.values()),
                            "prepared": dict(self._prepared),
                            # aliases pruned to live chains, so the
                            # map cannot grow past the open set
                            "aliases": self._live_aliases(),
                            "claim": (
                                dict(self._claim)
                                if self._claim
                                else None
                            ),
                        }
                        f.write(
                            _frame_line(json.dumps(ckpt, default=str))
                            + "\n"
                        )
                        REGISTRY.counter("journal.checkpoints").update()
                    f.write(line + "\n")
                    f.flush()
                    # durable-before-acknowledged: a recorded claim
                    # or admission the caller acts on must survive
                    # power loss, not just process death — flush
                    # alone leaves the frame in the page cache
                    faults.maybe_inject_io("fsync", self._cur_segment())
                    os.fsync(f.fileno())
                self._cur_count += 1
                if rotate:
                    self._gc_segments()
            except OSError:
                # a full/broken disk must never fail admission — the
                # journal degrades to best-effort (in-memory state
                # stays correct for checkpoints that do succeed)
                log.warning(
                    "journal append failed under %s", self.path,
                    exc_info=True,
                )
        REGISTRY.counter("journal.writes").update()

    def record_submit(
        self,
        qid: str,
        sql: str,
        user: str = "",
        prepared: Optional[Dict[str, str]] = None,
        resource_group: Optional[str] = None,
    ) -> None:
        """One admitted query (journaled BEFORE its execution thread
        can start, so finish can never precede submit on disk)."""
        self._append(
            {
                "ev": "submit",
                "qid": qid,
                "sql": sql,
                "user": user,
                "group": resource_group,
                "prepared": dict(prepared or {}),
            }
        )

    def record_finish(
        self, qid: str, state: str = "FINISHED", resumed_as: str = ""
    ) -> None:
        """Terminal close-out: FINISHED/FAILED, or RESUMED when a
        restarted coordinator re-admitted the query under a new id —
        ``resumed_as`` names that replacement, making the restart alias
        durable across FURTHER bounces."""
        rec = {"ev": "finish", "qid": qid, "state": state}
        if resumed_as:
            rec["resumed_as"] = resumed_as
        self._append(rec)

    def record_kill(
        self, qid: str, policy: str, reason: str, nbytes: int = 0
    ) -> None:
        """One cluster-memory-manager kill decision (server/
        memory_arbiter.py): pure audit trail — replay ignores it (the
        victim's terminal finish frame, or its re-admission's submit
        frame, carries the state the journal enforces)."""
        self._append(
            {
                "ev": "kill",
                "qid": qid,
                "policy": policy,
                "reason": reason,
                "bytes": int(nbytes),
            }
        )

    def record_suspend(
        self,
        qid: str,
        spooled_attempts: int = 0,
        running_stages: int = 0,
        suspensions: int = 1,
    ) -> None:
        """One QoS preempt-and-resume suspension (server/qos.py):
        pure audit trail, replay-inert — the parked query is still
        OPEN (its submit frame has no finish), so a coordinator bounce
        re-admits it exactly like any other non-terminal query. The
        frame records the victim's spooled progress (committed
        exchange-spool attempts + stages running at the decision), so
        an operator can see what a resume will reuse."""
        self._append(
            {
                "ev": "qos_suspend",
                "qid": qid,
                "spooled_attempts": int(spooled_attempts),
                "running_stages": int(running_stages),
                "suspensions": int(suspensions),
            }
        )

    def record_resume(self, qid: str, suspended_ms: float = 0.0) -> None:
        """The matching QoS resume close-out (audit trail, replay-
        inert)."""
        self._append(
            {
                "ev": "qos_resume",
                "qid": qid,
                "suspended_ms": float(suspended_ms),
            }
        )

    def record_claim(self, claimant: str, epoch: int) -> None:
        """One failover claim against THIS journal (written by the
        lease-fenced survivor, first, before any close-out): a
        restarted original owner replays it and learns it was claimed
        at ``epoch`` — its new lease must rejoin strictly above."""
        self._append(
            {"ev": "claim", "claimant": claimant, "epoch": int(epoch)}
        )

    def record_alias(self, old_qid: str, qid: str) -> None:
        """One inherited restart alias (written into the SURVIVOR's
        own journal at failover): ``old_qid`` — an id minted by a dead
        peer — now resolves to this coordinator's ``qid``. Makes the
        cross-coordinator alias chain durable past the survivor's own
        next bounce."""
        self._append({"ev": "alias", "old": old_qid, "qid": qid})

    def record_prepare(self, name: str, sql: str) -> None:
        self._append({"ev": "prepare", "name": name, "sql": sql})

    def record_deallocate(self, name: str) -> None:
        self._append({"ev": "deallocate", "name": name})

    # ------------------------------------------------------------- gc

    def _gc_segments(self) -> None:
        """Keep the newest two segments: the newest opens with a full
        checkpoint, the previous guards against a crash tearing that
        checkpoint mid-write (plan/history.py's discipline)."""
        for seg in self._segments()[:-2]:
            try:
                os.unlink(seg)
            except OSError:
                pass

    # ------------------------------------------------------------ read

    def replay(self) -> JournalState:
        """State reconstructed at construction time (the recovery API
        the coordinator consumes once, at start)."""
        return self._replayed

    # ----------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            return {
                "open_queries": len(self._open),
                "prepared": len(self._prepared),
                "segments": len(self._segments()),
                "writes": int(REGISTRY.counter("journal.writes").total),
            }
