"""Durable-exchange spool: the recovery plane under fault-tolerant
execution.

Reference parity: Presto/Trino fault-tolerant execution ("Project
Tardigrade") spools exchange data to external storage so that losing a
worker mid multi-stage query restarts only the LOST tasks — upstream
stages re-serve their already-produced pages from the spool instead of
re-running. Here the spool is a shared directory
(``exchange.spool-path``, the filesystem exchange plugin shape): every
worker tees its partitioned output-buffer pages into it as they are
produced, commits the attempt on task FINISH, and any worker (or a
replacement attempt on another worker) can re-serve a partition from
disk when the producer's node is gone.

Keying: deterministic task-attempt ids (:mod:`server.task_ids`). All
attempts of one logical task share a ``logical_key``; consumers take
exactly ONE committed attempt per key (attempt-id dedup), so a retry
racing its zombie original can never double-count.

On-disk layout (one directory, flat)::

    {task_attempt_id}.{partition}.pages   framed page stream
    {task_attempt_id}.ok                  commit marker (written LAST)

Frame: ``b"SPL1"`` once, then per page ``[u32 len][u32 crc32][payload]``
(checksum framing: a torn write or bit flip is detected at read time,
counted in ``spool.corrupt``, and the attempt is skipped — recovery
falls back to another committed attempt or degrades to a task re-run).

GC: committed attempts expire after ``exchange.spool-ttl-s`` and the
directory is bounded by ``exchange.spool-bytes`` (oldest committed
attempts evicted first). Occupancy surfaces in
``system.runtime.caches`` and the ``spool.*`` metrics.
"""

from __future__ import annotations

import os
import re
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional

from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY

_MAGIC = b"SPL1"
_FRAME = struct.Struct("<II")

#: default byte budget for the spool directory (exchange.spool-bytes)
DEFAULT_SPOOL_BYTES = 1 << 30
#: default time-to-live for committed attempts (exchange.spool-ttl-s)
DEFAULT_TTL_S = 600.0
#: default queue depth for the background tee drain
#: (exchange.spool-drain-depth)
DEFAULT_DRAIN_DEPTH = 64

#: ``{task_attempt_id}.{partition}.pages`` — task ids contain dots, so
#: the partition is the LAST dot-separated field before the suffix
_PAGES_RE = re.compile(r"^(?P<task>.+)\.(?P<part>\d+)\.pages$")


class SpoolDrain:
    """Background executor for the spool tee: the retry-TASK tee's
    SPL1 serialization (device->host fetch + partition slicing + frame
    writes) runs on ONE daemon thread per worker instead of the
    producer's device loop — durability stops charging the exchange
    hot path.

    Contract with the spool:

    - **Single appender preserved.** Every append of a drained task
      funnels through the one drain thread (worker.offer_page routes
      its inline tee here too when a drain is attached), so the
      spool's one-appender-per-``(task, part)`` file discipline holds
      even when a task's batches mix ICI and HTTP lanes.
    - **Commit-marker-last preserved.** The worker calls
      :meth:`flush` BEFORE ``spool.commit`` — the marker is still
      written after every frame of the attempt is on disk, and a
      failed tee unit surfaces at flush so the worker discards the
      attempt instead of committing a hole.
    - **Bounded.** ``submit`` applies backpressure (the producer
      waits) when ``depth`` units are queued: the drain bounds memory,
      it never drops durability work. After :meth:`close` (worker
      shutdown) units run inline on the caller.
    """

    def __init__(self, depth: int = DEFAULT_DRAIN_DEPTH):
        self.depth = max(1, int(depth))
        self._cond = threading.Condition()
        self._queue: List[tuple] = []  # (task_id, unit fn)
        self._pending: Dict[str, int] = {}  # task -> queued + running
        self._failed: Dict[str, str] = {}
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="spool-drain", daemon=True
        )
        self._thread.start()

    def submit(self, task_id: str, fn) -> None:
        """Queue one tee unit (a zero-argument closure owning its page
        references); blocks while the queue is at depth."""
        with self._cond:
            while len(self._queue) >= self.depth and not self._closed:
                self._cond.wait(0.1)
            if not self._closed:
                self._queue.append((task_id, fn))
                self._pending[task_id] = (
                    self._pending.get(task_id, 0) + 1
                )
                REGISTRY.counter("spool.drain_units").update()
                self._cond.notify_all()
                return
        # closed: shutdown path — durability outlives the drain thread
        fn()

    def flush(self, task_id: str, timeout: float = 60.0) -> None:
        """Wait until every unit of ``task_id`` has run; raises when
        any unit failed (or the wait times out) so the caller discards
        the spool attempt instead of committing it."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._pending.get(task_id, 0) > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"spool drain flush timed out for {task_id}"
                    )
                self._cond.wait(min(left, 0.1))
            err = self._failed.pop(task_id, None)
        if err is not None:
            raise RuntimeError(
                f"spool drain unit failed for {task_id}: {err}"
            )

    def forget(self, task_id: str) -> None:
        """Drop queued units of a dead task (its spool attempt is
        being discarded anyway; a unit already running just finishes
        against the doomed attempt)."""
        with self._cond:
            self._queue = [
                (t, fn) for t, fn in self._queue if t != task_id
            ]
            self._pending.pop(task_id, None)
            self._failed.pop(task_id, None)
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(0.2)
                if not self._queue:
                    return  # closed and drained
                task_id, fn = self._queue.pop(0)
                self._cond.notify_all()
            err = None
            try:
                fn()
            except Exception as exc:  # surfaced at flush
                err = f"{type(exc).__name__}: {exc}"
            with self._cond:
                left = self._pending.get(task_id, 0) - 1
                if left > 0:
                    self._pending[task_id] = left
                else:
                    self._pending.pop(task_id, None)
                if err is not None:
                    self._failed[task_id] = err
                self._cond.notify_all()

    def stats(self) -> dict:
        with self._cond:
            return {
                "queued": len(self._queue),
                "tasks": len(self._pending),
                "depth": self.depth,
            }


class ExchangeSpool:
    """Tee + re-serve exchange pages through a shared spool directory."""

    def __init__(
        self,
        path: str,
        budget_bytes: int = DEFAULT_SPOOL_BYTES,
        ttl_s: float = DEFAULT_TTL_S,
    ):
        self.path = path
        self.budget_bytes = int(budget_bytes)
        self.ttl_s = float(ttl_s)
        os.makedirs(path, exist_ok=True)
        self._lock = threading.RLock()
        self._last_gc = 0.0

    @staticmethod
    def from_config(config) -> Optional["ExchangeSpool"]:
        """Spool from tier-1 ``exchange.spool-*`` keys (None when no
        spool path is configured — the zero-cost default)."""
        if config is None:
            return None
        path = config.get("exchange.spool-path")
        if not path:
            return None
        from presto_tpu.utils.memory import parse_bytes

        raw = config.get("exchange.spool-bytes")
        ttl = config.get("exchange.spool-ttl-s")
        return ExchangeSpool(
            path,
            budget_bytes=(
                parse_bytes(raw) if raw is not None else DEFAULT_SPOOL_BYTES
            ),
            ttl_s=float(ttl) if ttl is not None else DEFAULT_TTL_S,
        )

    # ------------------------------------------------------------ naming

    def _pages_file(self, task_id: str, part: int) -> str:
        return os.path.join(self.path, f"{task_id}.{part}.pages")

    def _ok_file(self, task_id: str) -> str:
        return os.path.join(self.path, f"{task_id}.ok")

    # ------------------------------------------------------- produce side

    def append(self, task_id: str, part: int, page: bytes) -> None:
        """Tee one output-buffer page (called as the producer offers it;
        the attempt is not servable until :meth:`commit`).

        Lock-free by contract: exactly one producer thread appends per
        ``(task, part)`` file (worker.offer_page), readers only open
        COMMITTED attempts (commit happens after every append
        returned), and GC never removes an uncommitted attempt whose
        mtime is fresh — so concurrent tasks' tees need not serialize
        behind one instance lock on the exchange hot path."""
        fn = self._pages_file(task_id, part)
        new = not os.path.exists(fn)
        faults.maybe_inject_io("write", fn)
        with open(fn, "ab") as f:
            if new:
                f.write(_MAGIC)
            f.write(_FRAME.pack(len(page), zlib.crc32(page)))
            f.write(page)
        REGISTRY.counter("spool.pages_written").update()
        REGISTRY.counter("spool.bytes_written").update(len(page))

    def commit(self, task_id: str) -> None:
        """Mark the attempt complete — the marker is written LAST, so a
        crash mid-spool leaves an uncommitted (never served) attempt.

        Durable-before-acknowledged: every pages file is fsynced
        BEFORE the marker, and the marker before returning — a
        power loss after commit() must not leave a servable marker
        pointing at page frames still in the page cache (once per
        task, never per page: the tee stays off the hot path)."""
        prefix = task_id + "."
        for fn in self._listdir():
            if fn.startswith(prefix) and fn.endswith(".pages"):
                p = os.path.join(self.path, fn)
                faults.maybe_inject_io("fsync", p)
                try:
                    fd = os.open(p, os.O_RDONLY)
                except FileNotFoundError:
                    # vanished mid-scan (concurrent discard/GC): the
                    # marker below still only covers surviving files
                    continue
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
        with self._lock:
            ok = self._ok_file(task_id)
            faults.maybe_inject_io("write", ok)
            with open(ok, "wb") as f:
                f.write(b"ok")
                f.flush()
                os.fsync(f.fileno())
        REGISTRY.counter("spool.commits").update()
        # GC at commit (once per task), not per appended page: the
        # tee sits on the exchange hot path and must not pay a
        # directory scan per page
        self.gc()

    def discard(self, task_id: str) -> None:
        """Drop an attempt (FAILED/ABORTED tasks: their partial pages
        must never be served)."""
        with self._lock:
            self._remove_attempt(task_id)

    def _remove_attempt(self, task_id: str) -> None:
        # the .ok marker goes FIRST: a reader that still sees the
        # marker may rely on the pages files existing ("committed but
        # no pages file" reads as an empty partition) — un-commit
        # before touching any data file, mirroring commit's marker-last
        # ordering
        try:
            os.remove(self._ok_file(task_id))
        except OSError:
            pass
        prefix = task_id + "."
        for fn in self._listdir():
            if fn.startswith(prefix) and fn.endswith(".pages"):
                try:
                    os.remove(os.path.join(self.path, fn))
                except OSError:
                    pass

    def _listdir(self) -> List[str]:
        try:
            return os.listdir(self.path)
        except OSError:
            return []

    # ------------------------------------------------------- consume side

    def committed_for_query(self, query_id: str) -> int:
        """Committed attempts belonging to one query — the "spooled
        progress" a QoS suspension records in its journal frame
        (server/qos.py): every counted attempt's partitions will serve
        from the spool on resume instead of re-running, even if its
        worker dies while the query is parked."""
        prefix = query_id + "."
        with self._lock:
            return sum(
                1
                for fn in self._listdir()
                if fn.endswith(".ok") and fn.startswith(prefix)
            )

    def committed_attempts(self, logical_key: str) -> List[str]:
        """Committed attempt ids for one logical task, lowest attempt
        first (the deterministic dedup order)."""
        from presto_tpu.server import task_ids

        out = []
        with self._lock:
            for fn in self._listdir():
                if not fn.endswith(".ok"):
                    continue
                tid = fn[: -len(".ok")]
                if task_ids.logical_key(tid) == logical_key:
                    out.append(tid)
        out.sort(key=lambda t: (len(t), t))  # a2 < a10
        return out

    def serve(self, logical_key: str, part: int) -> Optional[List[bytes]]:
        """Pages of partition ``part`` from exactly ONE committed
        attempt of the logical task (``[]`` when the attempt produced
        no rows for that partition). ``None`` = nothing recoverable:
        no committed attempt, or every committed attempt corrupt."""
        for tid in self.committed_attempts(logical_key):
            fn = self._pages_file(tid, part)
            if not os.path.exists(fn):
                # committed attempt with no pages file: an empty
                # partition — UNLESS a concurrent GC un-committed the
                # attempt between our listing and this check (the
                # marker is always removed before any pages file, so a
                # still-present marker proves the files are intact)
                if not os.path.exists(self._ok_file(tid)):
                    continue
                REGISTRY.counter("spool.hits").update()
                return []
            try:
                pages = self._read_frames(fn, tid)
            except (ValueError, OSError):
                REGISTRY.counter("spool.corrupt").update()
                continue
            REGISTRY.counter("spool.hits").update()
            REGISTRY.counter("spool.pages_served").update(len(pages))
            REGISTRY.counter("spool.bytes_served").update(
                sum(len(p) for p in pages)
            )
            return pages
        REGISTRY.counter("spool.misses").update()
        return None

    def _read_frames(self, fn: str, task_id: str) -> List[bytes]:
        with self._lock:
            with open(fn, "rb") as f:
                buf = f.read()
        if buf[:4] != _MAGIC:
            raise ValueError(f"bad spool magic in {fn}")
        # chaos hook (``spool_corrupt`` rules): flip one payload byte
        # before verification, so the checksum path is the thing tested
        if faults.maybe_inject_spool(task_id) and len(buf) > _FRAME.size + 4:
            i = 4 + _FRAME.size
            buf = buf[:i] + bytes([buf[i] ^ 0xFF]) + buf[i + 1 :]
        pages: List[bytes] = []
        off = 4
        while off < len(buf):
            if off + _FRAME.size > len(buf):
                raise ValueError(f"torn spool frame header in {fn}")
            ln, crc = _FRAME.unpack_from(buf, off)
            off += _FRAME.size
            payload = buf[off : off + ln]
            off += ln
            if len(payload) != ln or zlib.crc32(payload) != crc:
                raise ValueError(f"spool frame checksum mismatch in {fn}")
            pages.append(payload)
        return pages

    # --------------------------------------------------------------- gc

    def gc(self, force: bool = False) -> None:
        """TTL expiry + byte-budget eviction (oldest committed attempts
        first). Throttled to once a second on the hot append path."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_gc < 1.0:
                return
            self._last_gc = now
            groups = self._scan()
            wall = time.time()
            # TTL: whole attempts whose newest file is older than ttl_s
            for tid, g in list(groups.items()):
                if wall - g["mtime"] > self.ttl_s:
                    self._remove_attempt(tid)
                    REGISTRY.counter("spool.expired").update()
                    del groups[tid]
            total = sum(g["bytes"] for g in groups.values())
            if total <= self.budget_bytes:
                return
            # budget: evict oldest COMMITTED attempts (an uncommitted
            # attempt is still being produced — never yank it mid-write)
            victims = sorted(
                (g for g in groups.values() if g["committed"]),
                key=lambda g: g["mtime"],
            )
            for g in victims:
                if total <= self.budget_bytes:
                    break
                self._remove_attempt(g["task_id"])
                REGISTRY.counter("spool.evicted").update()
                total -= g["bytes"]

    def _scan(self) -> Dict[str, dict]:
        """Attempt-id -> {bytes, mtime, committed} over the directory."""
        groups: Dict[str, dict] = {}

        def group(tid: str) -> dict:
            return groups.setdefault(
                tid,
                {
                    "task_id": tid,
                    "bytes": 0,
                    "mtime": 0.0,
                    "committed": False,
                },
            )

        for fn in self._listdir():
            path = os.path.join(self.path, fn)
            try:
                st = os.stat(path)
            except OSError:
                continue
            if fn.endswith(".ok"):
                g = group(fn[: -len(".ok")])
                g["committed"] = True
            else:
                m = _PAGES_RE.match(fn)
                if m is None:
                    continue
                g = group(m.group("task"))
                g["bytes"] += st.st_size
            g["mtime"] = max(g["mtime"], st.st_mtime)
        return groups

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Occupancy + counters for ``system.runtime.caches``."""
        with self._lock:
            groups = self._scan()
        return {
            "entries": sum(1 for g in groups.values() if g["committed"]),
            "bytes": sum(g["bytes"] for g in groups.values()),
            "budget_bytes": self.budget_bytes,
            "hits": int(REGISTRY.counter("spool.hits").total),
            "misses": int(REGISTRY.counter("spool.misses").total),
            "evictions": int(
                REGISTRY.counter("spool.evicted").total
                + REGISTRY.counter("spool.expired").total
            ),
        }
