"""Elastic worker pools: the autoscaler and the pool-provider SPI.

Reference parity: Presto's disaggregated-coordinator / elastic-cluster
direction treats membership as fluid — capacity is added when the queue
builds and drained away when it idles, and scale-down is a graceful
drain, never a kill (PAPER.md L3; the drain protocol of PR 5 is what
makes shrinking lossless). The autoscaler here is COORDINATOR-DRIVEN:
one control loop reads the admission queue depth, running-query count,
and stage backlog off the existing stats plane and asks a pluggable
:class:`WorkerPoolProvider` to spawn or drain workers within
``pool.min-workers``/``pool.max-workers``.

Decision shape (deterministic, unit-testable via :meth:`Autoscaler.step`):

- **floor**: below ``min_workers``, spawn unconditionally;
- **scale up**: queued queries waiting and headroom below
  ``max_workers`` — responsive, one worker per tick;
- **scale down**: only after ``scale_down_ticks`` CONSECUTIVE idle
  observations AND ``cooldown_s`` since the last action (hysteresis:
  oscillating load ratchets capacity up and holds it; it never flaps
  up-down-up), one worker per tick, newest provider-owned worker first,
  always through the worker's drain protocol (zero query loss).

Providers: :class:`presto_tpu.server.launcher.LocalWorkerPoolProvider`
ships the in-process shape (dev/bench/tests); real deployments
implement the same two-method SPI against their scheduler (k8s
replicas, GCE MIGs, TPU pod managers) — spawned capacity is typically
PREEMPTIBLE, which the scheduler already treats as first-class
(spool-backed producers on preemptibles, gather/merge on stable nodes;
see ``server/scheduler.stable_workers``).

Metrics: ``pool.{scale_up,scale_down,preemptions,resumed_queries,
spawn_failures}``, registered at construction so HELP/TYPE render
before the first event.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

from presto_tpu.utils.metrics import REGISTRY

log = logging.getLogger("presto_tpu.pool")


class WorkerPoolProvider:
    """SPI: how the autoscaler actually adds/removes capacity.

    Implementations must be idempotent-ish under races: ``drain`` of an
    already-gone node is a no-op, and a ``spawn`` failure raises (the
    autoscaler counts it and retries next tick)."""

    def spawn(self) -> str:
        """Start one worker pointed at the coordinator; returns its
        node id (used for discovery tracking and later drain)."""
        raise NotImplementedError

    def drain(self, node_id: str) -> None:
        """Gracefully drain one worker (the drain protocol: stop
        accepting, finish + serve/spool buffers, exit clean). Must not
        block the autoscaler tick — fire and forget."""
        raise NotImplementedError

    def owns(self, node_id: str) -> bool:
        """Is this worker still the provider's to manage? The
        autoscaler forgets owned workers that are BOTH missing from
        discovery and disowned here — a discovery-TTL flap alone (slow
        announce, wedged coordinator link) must not orphan a live
        worker the provider can still drain. Default: True (never
        disown on TTL evidence only)."""
        return True


class Autoscaler:
    """The coordinator's scale control loop (one daemon thread)."""

    def __init__(
        self,
        coordinator,
        provider: WorkerPoolProvider,
        min_workers: int = 0,
        max_workers: int = 0,
        interval_s: float = 1.0,
        scale_down_ticks: int = 3,
        cooldown_s: Optional[float] = None,
    ):
        self.coordinator = coordinator
        self.provider = provider
        self.min_workers = max(int(min_workers), 0)
        self.max_workers = max(int(max_workers), self.min_workers)
        self.interval_s = max(float(interval_s), 0.01)
        self.scale_down_ticks = max(int(scale_down_ticks), 1)
        #: after any scaling action, no scale-DOWN for this long (the
        #: other hysteresis half; scale-up stays immediate)
        self.cooldown_s = (
            2.0 * self.interval_s if cooldown_s is None else float(cooldown_s)
        )
        #: node ids this autoscaler spawned (newest last — the LIFO
        #: drain order); static workers are never drained
        self.owned: List[str] = []
        self.last_decision = ""
        self._idle_ticks = 0
        self._last_action = float("-inf")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # register the pool metric families up front so HELP/TYPE
        # render before the first scaling event
        for m in (
            "pool.scale_up",
            "pool.scale_down",
            "pool.preemptions",
            "pool.resumed_queries",
            "pool.spawn_failures",
        ):
            REGISTRY.counter(m)

    # -------------------------------------------------------- lifecycle

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # ---------------------------------------------------------- control

    def step(
        self,
        queued: int,
        running: int,
        backlog: int,
        n_workers: int,
        now: Optional[float] = None,
    ) -> str:
        """One deterministic control decision over the observed load
        (queued queries, running queries, RUNNING/QUEUED task backlog)
        and the current worker count (announced + still-booting).
        Returns — and records — the decision string the nodes view
        serves."""
        now = time.monotonic() if now is None else now
        busy = queued > 0 or running > 0 or backlog > 0
        self._idle_ticks = 0 if busy else self._idle_ticks + 1
        decision = "hold"
        if n_workers < self.min_workers:
            nid = self._spawn()
            decision = (
                f"scale_up(floor {n_workers}<{self.min_workers}): {nid}"
                if nid
                else "spawn_failed"
            )
            self._last_action = now
        elif queued > 0 and n_workers < self.max_workers:
            nid = self._spawn()
            decision = (
                f"scale_up(queued={queued}): {nid}"
                if nid
                else "spawn_failed"
            )
            self._last_action = now
        elif (
            not busy
            and n_workers > self.min_workers
            and self.owned
            and self._idle_ticks >= self.scale_down_ticks
            and now - self._last_action >= self.cooldown_s
        ):
            nid = self._drain_one()
            decision = f"scale_down(idle x{self._idle_ticks}): {nid}"
            self._last_action = now
            self._idle_ticks = 0
        self.last_decision = decision
        if self.coordinator is not None:
            self.coordinator.pool_decision = decision
        return decision

    def _spawn(self) -> Optional[str]:
        try:
            nid = self.provider.spawn()
        except Exception:
            REGISTRY.counter("pool.spawn_failures").update()
            log.warning("pool spawn failed", exc_info=True)
            return None
        self.owned.append(nid)
        if self.coordinator is not None:
            self.coordinator._pool_scaling.add(nid)
        REGISTRY.counter("pool.scale_up").update()
        log.info("pool scale-up: spawned %s", nid)
        return nid

    def _drain_one(self) -> str:
        nid = self.owned.pop()
        if self.coordinator is not None:
            self.coordinator._pool_scaling.discard(nid)
        try:
            self.provider.drain(nid)
        except Exception:
            log.warning("pool drain of %s failed", nid, exc_info=True)
        REGISTRY.counter("pool.scale_down").update()
        log.info("pool scale-down: draining %s", nid)
        return nid

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._tick()
            except Exception:
                log.warning("autoscaler tick failed", exc_info=True)

    def _tick(self) -> None:
        coord = self.coordinator
        snap = coord.load_snapshot()
        # discovery-level count (TTL-fresh ACTIVE announcements), NOT
        # active_workers(): a control-loop poll must never consume a
        # circuit breaker's half-open probe slot
        ids = {
            w.node_id
            for w in coord._ttl_workers()
            if w.state == "ACTIVE"
        }
        # a spawned worker that has announced is no longer SCALING_UP
        for nid in list(coord._pool_scaling):
            if nid in ids:
                coord._pool_scaling.discard(nid)
        # forget owned workers that are gone without our drain (killed,
        # preempted — the PROVIDER disowned them): draining a ghost
        # would count as a capacity change. A node merely absent from
        # discovery (TTL flap) stays owned — see WorkerPoolProvider.owns
        self.owned = [
            nid
            for nid in self.owned
            if nid in ids
            or nid in coord._pool_scaling
            or self.provider.owns(nid)
        ]
        pending = sum(
            1 for nid in self.owned if nid in coord._pool_scaling
        )
        self.step(
            snap["queued"],
            snap["running"],
            snap["backlog"],
            len(ids) + pending,
        )
