"""Worker process: executes plan fragments over its local device mesh.

Reference parity: the worker task runtime — ``TaskResource``
(``POST /v1/task/{id}``), ``SqlTaskManager``, task status long-poll, the
producer side of the paged exchange (``OutputBuffer`` +
``GET /v1/task/{id}/results/{buffer}/{token}``), graceful shutdown
(SURVEY.md §2.1 "Task runtime", §2.5, §5.3). The C++ native worker
("Prestissimo") implements exactly this HTTP surface; here the device
runtime is JAX over the worker's local chips, and the HTTP host agent
is this module.

Execution: a task = FragmentSpec (plan fragment + owned row range of the
partitioned scan). Replicated scans load in full; the partitioned scan
loads only the owned range. The whole fragment compiles to one XLA
program over the local mesh (the in-slice engine); result pages are
serialized into the task's output buffer, pulled token-acked by the
coordinator, and freed on DELETE.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import traceback
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from presto_tpu.connectors.spi import ConnectorSplit
from presto_tpu.exec.staging import (
    bucket_capacity,
    page_nbytes,
    stage_page,
)
from presto_tpu.exec.stats import TaskStats
from presto_tpu.plan import nodes as N
from presto_tpu.server import exchange_spi, pages_wire, rpc, task_ids
from presto_tpu.server.protocol import FragmentSpec
from presto_tpu.server.spool import (
    DEFAULT_DRAIN_DEPTH,
    ExchangeSpool,
    SpoolDrain,
)
from presto_tpu.utils import devicediag, faults, tracing
from presto_tpu.utils.metrics import REGISTRY

log = logging.getLogger("presto_tpu.worker")


class WorkerDraining(RuntimeError):
    """New-task rejection while the worker drains (or shuts down):
    surfaced to the coordinator as HTTP 503, which reschedules the
    task on another worker instead of failing the query."""

#: rows per exchange page (the reference pages its exchange similarly)
PAGE_ROWS = 1 << 16

#: max unacked pages buffered per task before the producer blocks
#: (reference: bounded OutputBuffer / sink.max-buffer-size blocking the
#: producer driver, SURVEY.md §2.5 "Backpressure")
MAX_BUFFERED_PAGES = 64


def _offer_chunked(task: "_Task", cols, n: int) -> None:
    """Serialize wire columns into PAGE_ROWS-sized pages on the task's
    output buffer — the ONE chunk-and-offer loop every result-emitting
    path shares (streaming emit, single- and multi-remote merges)."""
    for lo in range(0, max(n, 1), PAGE_ROWS):
        hi = min(lo + PAGE_ROWS, n)
        chunk = [
            (name, d[lo:hi], None if v is None else v[lo:hi], t, dv)
            for name, d, v, t, dv in cols
        ]
        task.offer_page(pages_wire.serialize_page(chunk, hi - lo))
        with task.cond:
            task.stats.output_rows += hi - lo


class _Task:
    def __init__(
        self, spec: FragmentSpec, pool=None, node_id: str = "",
        spool: "ExchangeSpool" = None, drain: "SpoolDrain" = None,
    ):
        self.spec = spec
        self.state = "QUEUED"  # QUEUED|RUNNING|FINISHED|FAILED|ABORTED
        self.error: Optional[str] = None
        #: per-task stats, shipped back in /v1/task/{id}/status
        #: (reference: TaskStats on the task-status response)
        self.stats = TaskStats(
            task_id=spec.task_id,
            query_id=spec.query_id,
            node_id=node_id,
            create_time=time.time(),
        )
        #: trace context propagated by the coordinator (the handler
        #: folds the ``traceparent`` HTTP header into the spec)
        self.trace_ctx = tracing.parse_traceparent(spec.traceparent)
        #: synthesized span dicts, filled at task end (status payload)
        self.spans: List[dict] = []
        # one output buffer per partition (reference:
        # PartitionedOutputBuffer); unpartitioned tasks use buffer 0
        nparts = max(spec.n_partitions, 1)
        self.parts: List[List[Optional[bytes]]] = [
            [] for _ in range(nparts)
        ]
        self.part_acked: List[int] = [0] * nparts
        #: durable-exchange spool (fault-tolerant execution): tee this
        #: task's PARTITIONED output pages so a consumer can re-serve
        #: them after this worker dies; committed at FINISH
        self._spool = spool if spec.spool and nparts > 1 else None
        #: background tee drain: when attached, EVERY spool append of
        #: this task funnels through its one thread (single-appender
        #: contract), and _run_task flushes it before the commit
        self._spool_drain = drain if self._spool is not None else None
        self.spooled = False  # committed to the spool
        #: per-partition "consumer saw X-Complete" flags — the drain
        #: protocol waits on these (a draining worker must not exit
        #: under a consumer still pulling). ICI consumers flip them
        #: through the segment's consumed callback.
        self.complete_served: List[bool] = [False] * nparts
        #: in-slice exchange degrade-to-HTTP latch: materialization of
        #: this task's device-resident partitions into the serialized
        #: buffers runs exactly once, and concurrent result pulls block
        #: on it (a half-materialized buffer must never serve)
        self._ici_mat_lock = threading.Lock()
        self._ici_mat_done = False
        self.cond = threading.Condition()
        self.created = time.time()
        # buffered output bytes are accounted against the worker's
        # MemoryPool under a task-scoped key: buffers outlive task
        # FINISH (shuffle consumers attach later), so the query-id
        # safety-net release at task end must not free them
        self.pool = pool
        self.buf_key = f"{spec.query_id}#buf#{spec.task_id}"
        # merge tasks: dynamically-attached upstream sources
        # (reference: addExchangeLocations + noMoreExchangeLocations)
        self.sources: List[tuple] = [tuple(s) for s in spec.sources]
        self.sources_done: bool = bool(spec.sources)
        #: dynamic-filter summary (JSON dict) of a dynfilter_keys task,
        #: set when the task finishes; shipped on the status response
        self.dynfilter: Optional[dict] = None

    def add_sources(self, sources, done: bool) -> None:
        with self.cond:
            known = set(self.sources)
            for s in sources:
                s = tuple(s)
                if s not in known:
                    self.sources.append(s)
                    known.add(s)
            if done:
                self.sources_done = True
            self.cond.notify_all()

    def drop_buffers(self) -> None:
        """Release every remaining buffered byte (DELETE/abort path)."""
        if self.pool is not None:
            self.pool.release(self.buf_key, None)

    @property
    def pages(self) -> List[Optional[bytes]]:
        """Buffer 0 view (status reporting + unpartitioned pulls)."""
        return self.parts[0]

    def offer_page(self, page: bytes, part: int = 0) -> None:
        """Producer side: blocks while the buffer is full (backpressure);
        raises if the task was aborted while blocked.

        Partitioned (shuffle) buffers are stage-lifetime and exempt
        from the bounded-buffer wait: the merge stage attaches
        asynchronously (pipelined start) with no guarantee of pulling
        before this producer FINISHES, so blocking on a full buffer
        could deadlock the stage. They hold compressed PARTIAL states
        (small by construction) and every buffered byte is accounted
        against the MemoryPool — a too-big shuffle fails on accounting,
        not OOM. The bounded-buffer backpressure applies to the
        unpartitioned streaming path."""
        if self.pool is not None:
            # too-big shuffle output fails on ACCOUNTING
            # (MemoryLimitExceeded -> task FAILED), not on OOM. The
            # reserve runs BEFORE taking task.cond: a governance-lane
            # reserve may block waiting for headroom (and pressure
            # hooks may run spill DMA), and the condition guards the
            # result-serving handler threads — the same discipline as
            # the spool tee below. Only the producer thread appends
            # per (task, part), so nothing races the buffered bytes
            # between the reserve and the append; the abort path below
            # returns the reservation.
            self.pool.reserve(self.buf_key, len(page))
        try:
            with self.cond:
                while (
                    len(self.parts) == 1
                    and len(self.parts[part]) - self.part_acked[part]
                    >= MAX_BUFFERED_PAGES
                    and self.state == "RUNNING"
                ):
                    self.cond.wait(timeout=0.1)
                if self.state == "ABORTED":
                    raise RuntimeError("task aborted")
                self.parts[part].append(page)
                self.stats.output_bytes += len(page)
        except BaseException:
            # the page never reached the buffer: its reservation must
            # not leak into the task's release-all at teardown
            if self.pool is not None:
                self.pool.release(self.buf_key, len(page))
            raise
        # the spool tee runs OUTSIDE task.cond: disk I/O under the
        # condition would block the result-serving handler threads
        # behind every spooled page. Safe because pages are immutable
        # once buffered, the appends of one (task, part) all run on one
        # thread (the producer, or the drain when one is attached —
        # routing through the drain here keeps that true even when a
        # task's batches mix ICI and HTTP lanes), and commit (in
        # _run_task's finally) flushes the drain first
        if self._spool is not None:
            if self._spool_drain is not None:
                spool, tid = self._spool, self.spec.task_id

                def tee(page=page, part=part):
                    spool.append(tid, part, page)

                self._spool_drain.submit(tid, tee)
            else:
                self._spool.append(self.spec.task_id, part, page)

    def ack_below(self, token: int, part: int = 0) -> None:
        """Consumer side: pulling token N acks pages < N.

        Unpartitioned (streaming) buffers FREE acked pages — that is
        the backpressure contract. Partitioned (shuffle) buffers only
        advance the cursor: pages stay until DELETE, so a merge task
        retried on another worker can restart its pull at token 0
        without finding acked holes (silent data loss)."""
        with self.cond:
            pages = self.parts[part]
            if len(self.parts) == 1:
                freed = 0
                for i in range(
                    self.part_acked[part], min(token, len(pages))
                ):
                    if pages[i] is not None:
                        freed += len(pages[i])
                    pages[i] = None
                if freed and self.pool is not None:
                    self.pool.release(self.buf_key, freed)
            if token > self.part_acked[part]:
                self.part_acked[part] = token
            self.cond.notify_all()

    def abort(self) -> None:
        with self.cond:
            if self.state in ("QUEUED", "RUNNING"):
                self.state = "ABORTED"
            self.cond.notify_all()


class WorkerServer:
    """One worker process: HTTP host agent + local device execution."""

    def __init__(
        self,
        port: int = 0,
        node_id: Optional[str] = None,
        catalogs=None,
        coordinator_uri: Optional[str] = None,
        config=None,
        preemptible: Optional[bool] = None,
    ):
        from presto_tpu.exec.local_runner import LocalQueryRunner
        from presto_tpu.utils.memory import MemoryPool, parse_bytes

        from presto_tpu.exec.staging import DEFAULT_CACHE_BYTES

        self.node_id = node_id or f"worker-{uuid.uuid4().hex[:8]}"
        # memory accounting is ALWAYS on (reference: MemoryPool wired
        # unconditionally in the worker; limit from tier-1 config)
        limit = parse_bytes(
            (config.get("query.max-memory-per-node") if config else None)
            or "8GB"
        )
        self.memory_pool = MemoryPool(limit)
        self.memory_pool.node_id = self.node_id
        # cluster memory governance (server/memory_arbiter.py): with
        # the gate ON, an over-budget reservation BLOCKS (visible on
        # the heartbeat report, resolvable by the coordinator's
        # low-memory killer) instead of failing outright; OFF is the
        # bit-exact fail-fast legacy path
        self._governance = bool(
            config.get("memory.governance-enabled", False)
            if config
            else False
        )
        if self._governance:
            self.memory_pool.block_timeout_s = float(
                config.get("memory.reserve-block-max-s", 30.0)
            )
        # device-resident split cache (tier-1: staging.cache-bytes,
        # 0 disables): the LRU byte budget + try_reserve discipline
        # make always-on safe on the worker hot path — repeated
        # queries over the same split ranges skip the connector read
        # and the host->device transfer entirely
        cache_raw = (
            config.get("staging.cache-bytes") if config else None
        )
        cache_bytes = (
            parse_bytes(cache_raw)
            if cache_raw is not None
            else DEFAULT_CACHE_BYTES
        )
        self.runner = LocalQueryRunner(
            catalogs=catalogs,
            memory_pool=self.memory_pool,
            staging_cache_bytes=cache_bytes,
        )
        if cache_bytes > 0:
            self.runner.session.set("stream_split_cache", True)
        # host-spill lane (degrade before you kill): under HBM
        # pressure, evicted split-cache pages offload to a host-RAM
        # pool of this budget and restage on demand — gated with the
        # governance plane so the default stays bit-exact pre-PR
        if self._governance:
            spill_raw = (
                config.get("memory.host-spill-bytes") if config else None
            )
            if spill_raw is not None:
                self.runner.split_cache.set_spill_budget(
                    parse_bytes(spill_raw)
                )
        prefetch = (
            config.get("staging.prefetch-depth") if config else None
        )
        if prefetch is not None:
            self.runner.session.set(
                "staging_prefetch_depth", int(prefetch)
            )
        # parameterized plan cache (plan/canonical.py): the worker's
        # share is fragment CANONICALIZATION — literal-variant fragments
        # of one shape hit this runner's compile cache — gated by the
        # same tier-1 keys as the coordinator
        pcen = config.get("plan.cache-enabled") if config else None
        if pcen is not None:
            self.runner.session.set("enable_plan_cache", bool(pcen))
        pce = config.get("plan.cache-entries") if config else None
        if pce is not None:
            self.runner.plan_cache.resize(int(pce))
        # per-operator observability (exec/stats.OperatorStats): worker
        # programs trace per-node row counters into TaskStats.operators,
        # shipped on the status response and rolled into QueryInfo —
        # the same tier-1 gate as the coordinator. The history STORE
        # stays coordinator-side (queries complete there); workers only
        # measure.
        opstats = (
            config.get("operator-stats.enabled") if config else None
        )
        if opstats is not None:
            self.runner.session.set(
                "enable_operator_stats", bool(opstats)
            )
        self.tasks: Dict[str, _Task] = {}
        self._lock = threading.Lock()
        self._shutting_down = False
        # multi-coordinator discovery: one URI, a comma-separated
        # string, or a sequence — the worker heartbeats EVERY
        # coordinator (each runs its own arbiter/scheduler view), so
        # any survivor of a coordinator failover already knows this
        # worker. coordinator_uri keeps the first entry for existing
        # callers.
        if isinstance(coordinator_uri, str):
            self.coordinator_uris = [
                u.strip().rstrip("/")
                for u in coordinator_uri.split(",")
                if u.strip()
            ]
        else:
            self.coordinator_uris = [
                str(u).strip().rstrip("/")
                for u in (coordinator_uri or [])
                if str(u).strip()
            ]
        self.coordinator_uri = (
            self.coordinator_uris[0] if self.coordinator_uris else None
        )
        self._announcer: Optional[threading.Thread] = None
        # orphan-task reaper (task.orphan-ttl-s, 0 = off): announce
        # acks carry the answering coordinator's BOOT nonce, and every
        # qid embeds the boot of the coordinator that minted it — a
        # task whose minting boot has not been heard from in TTL is
        # orphaned (its coordinator died or was replaced; a failover
        # peer re-runs the query under ITS boot) and is deleted so a
        # dead fleet's buffers never pin worker memory
        self._orphan_ttl_s = float(
            config.get("task.orphan-ttl-s", 0.0) if config else 0.0
        )
        #: coordinator boot nonce -> last monotonic time heard from
        self._boot_seen: Dict[str, float] = {}
        # fault-tolerance plane: one RPC policy for worker->worker
        # shuffle pulls, config-driven announce cadence/timeout
        self._rpc_policy = rpc.RpcPolicy.from_config(config)
        self._announce_interval = float(
            config.get("announcement.interval-s", 1.0) if config else 1.0
        )
        self._announce_timeout = float(
            config.get("announcement.timeout-s", 5.0) if config else 5.0
        )
        fault_spec = (
            config.get("fault-injection.spec") if config else None
        )
        if fault_spec:
            faults.configure(fault_spec)
        # durable-exchange spool (fault-tolerant execution): a shared
        # directory every node mounts (exchange.spool-path); None when
        # unconfigured — retry_policy=NONE never touches it
        self.spool = ExchangeSpool.from_config(config)
        # off-hot-path spool tee: one background drain thread per
        # worker batches the retry-TASK tee's SPL1 serialization so
        # durability stops charging the device loop; _run_task flushes
        # it before the commit marker (commit-marker-last unchanged)
        self.spool_drain = (
            SpoolDrain(
                int(
                    config.get(
                        "exchange.spool-drain-depth",
                        DEFAULT_DRAIN_DEPTH,
                    )
                    if config
                    else DEFAULT_DRAIN_DEPTH
                )
            )
            if self.spool is not None
            else None
        )
        # single-program collective stages: gate for the one-dispatch
        # shard_map exchange + the ICI coordinator-gather publish (the
        # collective path always fails open to the per-source gather)
        self.single_program = bool(
            config.get("exchange.single-program", True)
            if config
            else True
        )
        # in-slice collective shuffle (server/exchange_spi.py): the
        # slice identity this worker announces — workers sharing one
        # slice exchange partitioned output device-to-device through
        # the process-local segment; the default identity IS that
        # co-location (platform + host process). Config override for
        # explicit topologies; a wrong override is safe (segment miss
        # -> HTTP fallback).
        self.slice_id = str(
            (config.get("exchange.slice-id") if config else None)
            or exchange_spi.default_slice_id()
        )
        self._draining = False
        self._drain_grace_s = float(
            config.get("drain.grace-s", 30.0) if config else 30.0
        )
        # preemptible capacity (elastic pools): announced to discovery
        # so the scheduler places gather/merge stages on stable nodes;
        # a preemption notice drains with this SHORT grace window
        self.preemptible = bool(
            preemptible
            if preemptible is not None
            else (config.get("node.preemptible", False) if config else False)
        )
        self._preempt_grace_s = float(
            config.get("pool.preempt-grace-s", 10.0) if config else 10.0
        )

        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self.uri = f"http://127.0.0.1:{self.port}"
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "WorkerServer":
        # boot-time device probe (utils/devicediag.py): once per
        # process — the structured diagnosis rides every announcement
        # and /v1/status from then on
        if devicediag.last_diag() is None:
            devicediag.probe_backend()
        self._serve_thread.start()
        if self.coordinator_uris:
            self._announcer = threading.Thread(
                target=self._announce_loop, daemon=True
            )
            self._announcer.start()
        if self._orphan_ttl_s > 0:
            threading.Thread(
                target=self._reaper_loop, daemon=True
            ).start()
        return self

    def shutdown(self, graceful: bool = True) -> None:
        """Graceful: stop accepting work, finish running tasks, stop
        (reference: SHUTTING_DOWN protocol, SURVEY.md §5.3)."""
        self._shutting_down = True
        if graceful:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                with self._lock:
                    busy = any(
                        t.state in ("QUEUED", "RUNNING")
                        for t in self.tasks.values()
                    )
                if not busy:
                    break
                time.sleep(0.05)
        # Only handshake with serve_forever if it actually ran (see
        # CoordinatorServer.shutdown).
        if self.spool_drain is not None:
            self.spool_drain.close()
        if self._serve_thread.is_alive():
            self.httpd.shutdown()
        self.httpd.server_close()

    # ------------------------------------------------------------- drain

    def drain(self, grace_s: Optional[float] = None) -> None:
        """Graceful drain (``PUT /v1/state/drain``; SIGTERM in the
        launcher): stop accepting tasks (503 to new POSTs — the
        coordinator reschedules them), announce ``DRAINING`` so the
        coordinator stops scheduling here, keep serving result pulls
        until every finished task's buffers are consumed or spooled,
        then exit clean — a rolling restart under live load loses zero
        queries (reference: the SHUTTING_DOWN protocol, upgraded with
        the durable-exchange spool)."""
        with self._lock:
            if self._draining or self._shutting_down:
                return
            self._draining = True
        REGISTRY.counter("worker.drains").update()
        log.info("node=%s draining", self.node_id)
        # flip discovery NOW instead of waiting out the announce cadence
        self._announce_once()
        # chaos hook: kill_worker_draining crashes us mid-drain (the
        # protocol must stay recoverable — consumers fall back to the
        # spool / task retry)
        faults.maybe_inject_drain(self.node_id, kill=self._fault_kill)
        # ICI edges degrade to HTTP: serialize every FINISHED task's
        # device-resident partitions into its output buffers so any
        # consumer that has not taken its partition in-slice can still
        # pull it over the wire (still-RUNNING tasks materialize
        # themselves at seal time — they observe _draining)
        with self._lock:
            tasks = list(self.tasks.values())
        for t in tasks:
            if t.spec.ici_slice:
                with t.cond:
                    finished = t.state == "FINISHED"
                if finished:
                    try:
                        self._materialize_ici(t)
                    except Exception:
                        log.warning(
                            "node=%s drain ICI materialize failed for "
                            "%s", self.node_id, t.spec.task_id,
                            exc_info=True,
                        )
        grace = self._drain_grace_s if grace_s is None else grace_s
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline and not self._shutting_down:
            if not self._drain_busy():
                break
            time.sleep(0.05)
        log.info("node=%s drain complete, exiting", self.node_id)
        self.shutdown(graceful=False)

    def preempt(self, grace_s: Optional[float] = None) -> None:
        """Preemption notice (the cloud's SIGTERM-with-short-grace on
        preemptible capacity): an IMMEDIATE graceful drain bounded by
        ``pool.preempt-grace-s`` — announce DRAINING now (the
        coordinator reschedules everything new), finish what fits in
        the grace window, serve/spool finished buffers, exit. Running
        producers that spooled stay recoverable even when the grace
        expires mid-task (retry_policy=TASK re-runs only the lost
        work)."""
        with self._lock:
            if self._draining or self._shutting_down:
                return
        REGISTRY.counter("pool.preemptions").update()
        log.warning(
            "node=%s preemption notice: draining (grace %.1fs)",
            self.node_id,
            self._preempt_grace_s if grace_s is None else grace_s,
        )
        self.drain(
            grace_s=self._preempt_grace_s if grace_s is None else grace_s
        )

    def _fault_preempt(self) -> None:
        """Background preemption for the ``kill_worker_preempt`` fault
        rule: the notice arrives WHILE a task runs (the hook fires at
        task execute), so the drain must not block that task's
        thread."""
        threading.Thread(target=self.preempt, daemon=True).start()

    def _drain_busy(self) -> bool:
        """Anything left that exiting now would lose? Running/queued
        tasks; a FINISHED task whose buffers a consumer is still
        pulling (unless the spool holds a committed copy). FAILED and
        ABORTED buffers die with the worker by design."""
        with self._lock:
            tasks = list(self.tasks.values())
        for t in tasks:
            if t.state in ("QUEUED", "RUNNING"):
                return True
            if t.state != "FINISHED":
                continue
            with t.cond:
                if t.spooled:
                    continue  # durable copy outlives this worker
                if not all(t.complete_served):
                    return True
        return False

    def _announce_state(self) -> str:
        return "DRAINING" if self._draining else "ACTIVE"

    def _memory_report(self) -> dict:
        """Per-query/per-owner memory accounting for the heartbeat
        (cluster memory governance: the coordinator's arbiter folds
        these into its cluster view) — the shared
        ``rollup_query_report`` fold over this node's pool snapshot
        plus the host-spill occupancy."""
        from presto_tpu.exec.staging import SplitCache
        from presto_tpu.utils.memory import rollup_query_report

        return rollup_query_report(
            self.memory_pool.snapshot(),
            SplitCache.OWNER,
            self.runner.split_cache.spill_used_bytes(),
        )

    def _announce_body(self) -> dict:
        return {
            "node_id": self.node_id,
            "uri": self.uri,
            "state": self._announce_state(),
            "preemptible": self.preemptible,
            # slice/device-coordinate identity: the scheduler groups
            # co-located workers by slice id and plans their
            # partitioned exchanges as device collectives
            "slice_id": self.slice_id,
            "device_coords": exchange_spi.device_coords(),
            "memory": self._memory_report(),
            # boot-time device probe: the coordinator keeps the last
            # non-empty diagnosis per node (system.runtime.nodes)
            "backend_diag": devicediag.last_diag_dict(),
        }

    def _announce_once(self) -> None:
        """One best-effort, no-retry announcement to every coordinator
        (drain flips state immediately; failures fall back to the
        regular loop)."""
        body = self._announce_body()
        for uri in self.coordinator_uris:
            try:
                resp = rpc.call_json(
                    "PUT",
                    uri + "/v1/announcement",
                    body,
                    policy=rpc.RpcPolicy(
                        timeout_s=self._announce_timeout, retries=0
                    ),
                )
                self._saw_boot(resp)
            except Exception:
                pass

    def _saw_boot(self, resp) -> None:
        """Record the announce ack's coordinator boot nonce — the
        orphan reaper's liveness evidence per minting incarnation."""
        boot = (resp or {}).get("boot") if isinstance(resp, dict) else None
        if boot:
            self._boot_seen[str(boot)] = time.monotonic()

    #: announce backoff cap: a worker never goes quieter than this, so
    #: a recovered coordinator re-discovers it within ~2 TTLs
    ANNOUNCE_MAX_BACKOFF_S = 16.0

    def _announce_backoff(self, fails: int) -> float:
        """Delay before the next announcement: the healthy interval at
        ``fails == 0``, else jittered exponential backoff over
        [interval, min(interval * 2^fails, cap)] — never faster than
        the healthy cadence, never synchronized across peers (full
        jitter), never quieter than ANNOUNCE_MAX_BACKOFF_S."""
        if fails <= 0:
            return self._announce_interval
        cap = min(
            self._announce_interval * (2.0 ** min(fails, 6)),
            self.ANNOUNCE_MAX_BACKOFF_S,
        )
        return self._announce_interval + rpc.backoff_rng().uniform(
            0.0, max(cap - self._announce_interval, 0.0)
        )

    def _announce_loop(self):
        """Heartbeat to discovery — EVERY coordinator, each with its
        own failure count. A healthy loop announces every
        ``announcement.interval-s``; after consecutive failures to one
        coordinator its delay backs off exponentially (capped,
        resetting on success) — a fleet of workers must not hammer a
        restarting coordinator in lockstep (thundering herd). With
        peers, one dead coordinator backs ITS cadence off without
        quieting the heartbeats the live ones depend on: the loop
        wakes at the soonest per-coordinator due time."""
        fails = {u: 0 for u in self.coordinator_uris}
        due = {u: 0.0 for u in self.coordinator_uris}
        while not self._shutting_down:
            now = time.monotonic()
            body = self._announce_body()
            for uri in self.coordinator_uris:
                if now < due[uri]:
                    continue
                try:
                    # the loop IS the retry policy: no rpc-level
                    # retries, or backoff would stack on backoff
                    resp = rpc.call_json(
                        "PUT",
                        uri + "/v1/announcement",
                        body,
                        policy=rpc.RpcPolicy(
                            timeout_s=self._announce_timeout, retries=0
                        ),
                    )
                    self._saw_boot(resp)
                    fails[uri] = 0
                except Exception:
                    fails[uri] += 1
                    REGISTRY.counter("worker.announce_failures").update()
                due[uri] = time.monotonic() + self._announce_backoff(
                    fails[uri]
                )
            delay = max(min(due.values()) - time.monotonic(), 0.05)
            # sleep in short slices so shutdown is prompt even when
            # backed far off
            deadline = time.monotonic() + delay
            while (
                not self._shutting_down
                and time.monotonic() < deadline
            ):
                time.sleep(min(0.2, delay))

    def _reaper_loop(self) -> None:
        """Orphan-task reaper (``task.orphan-ttl-s``): delete tasks
        whose minting coordinator incarnation (the boot nonce embedded
        in every qid) has not been heard from — announce ack or new
        task — within the TTL. Rides the ONE task-teardown primitive
        (delete_task), so buffers, reservations, and in-slice segment
        entries all free."""
        while not self._shutting_down:
            time.sleep(min(self._orphan_ttl_s / 4.0, 1.0))
            now = time.monotonic()
            with self._lock:
                snap = [
                    (tid, t.spec.query_id, t.created_ts)
                    for tid, t in self.tasks.items()
                ]
            for tid, qid, created in snap:
                boot = task_ids.boot_of_query(qid)
                if not boot:
                    continue  # not a coordinator-minted qid: never reap
                seen = max(self._boot_seen.get(boot, 0.0), created)
                if now - seen <= self._orphan_ttl_s:
                    continue
                if self.delete_task(tid):
                    REGISTRY.counter("worker.orphans_reaped").update()
                    log.warning(
                        "node=%s reaped orphan task %s (coordinator "
                        "boot %s silent %.1fs)",
                        self.node_id, tid, boot, now - seen,
                    )

    def _fault_kill(self) -> None:
        """Abrupt crash for the fault plane's ``kill_worker`` action:
        stop announcing and close the socket WITHOUT draining, so every
        in-flight coordinator RPC sees a dead peer (connection refused)
        — a real crash, not the graceful SHUTTING_DOWN protocol."""
        self._shutting_down = True
        try:
            if self._serve_thread.is_alive():
                self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:
            pass
        log.warning("node=%s fault plane killed this worker", self.node_id)

    # ---------------------------------------------------------- task exec

    def create_task(self, spec: FragmentSpec) -> str:
        if self._draining or self._shutting_down:
            raise WorkerDraining("worker is draining")
        task = _Task(
            spec, pool=self.memory_pool, node_id=self.node_id,
            spool=self.spool, drain=self.spool_drain,
        )
        # orphan-reaper bookkeeping: the task itself is liveness
        # evidence for its minting coordinator boot (a coordinator
        # actively scheduling is not an orphan-maker even if this
        # worker's announce acks lag)
        task.created_ts = time.monotonic()
        boot = task_ids.boot_of_query(spec.query_id)
        if boot:
            self._boot_seen[boot] = task.created_ts
        with self._lock:
            self.tasks[spec.task_id] = task
        threading.Thread(
            target=self._run_task, args=(task,), daemon=True
        ).start()
        REGISTRY.counter("worker.tasks_created").update()
        return spec.task_id

    def _run_task(self, task: _Task) -> None:
        task.state = "RUNNING"
        task.stats.state = "RUNNING"
        trace_id = task.trace_ctx[0] if task.trace_ctx else ""
        log.info(
            "trace=%s task=%s node=%s state=RUNNING",
            trace_id, task.spec.task_id, self.node_id,
        )
        t0 = time.perf_counter()
        # this thread's engine-stats sink: the runner attributes
        # staging time, input rows/bytes, compile-cache hits, and
        # capacity-overflow retries to the active task
        self.runner._qs_local.value = task.stats
        outcome = "FINISHED"
        try:
            with REGISTRY.timer("worker.task_time").time():
                self._execute(task)
        except Exception as e:  # report to coordinator via status
            outcome = "FAILED"
            task.error = (
                f"{type(e).__name__}: {e}\n{traceback.format_exc()[-1000:]}"
            )
            REGISTRY.counter("worker.tasks_failed").update()
        finally:
            self.runner._qs_local.value = None
            task.stats.state = outcome
            task.stats.end_time = time.time()
            task.stats.wall_ms = (time.perf_counter() - t0) * 1000.0
            if task.trace_ctx is not None:
                task.spans = tracing.synthesize_task_spans(
                    trace_id=task.trace_ctx[0],
                    parent_span_id=task.trace_ctx[1],
                    task_id=task.spec.task_id,
                    node_id=self.node_id,
                    start=task.stats.create_time,
                    end=task.stats.end_time,
                    staging_ms=task.stats.staging_ms,
                    execute_ms=task.stats.execute_ms,
                    prefetch_ms=task.stats.prefetch_ms,
                )
            # seal the spooled attempt BEFORE the terminal state is
            # visible: FINISHED must imply the durable copy is complete
            # (consumers that see FINISHED may rely on the spool the
            # instant this worker dies); failed/aborted partial pages
            # must never serve
            if task._spool is not None:
                try:
                    if outcome == "FINISHED" and task.state != "ABORTED":
                        # drain flush BEFORE the commit marker: every
                        # teed frame must be on disk (and none failed)
                        # when the marker appears — a failed unit
                        # raises here and the attempt is discarded
                        # below instead of committed with a hole
                        if task._spool_drain is not None:
                            task._spool_drain.flush(task.spec.task_id)
                        task._spool.commit(task.spec.task_id)
                        task.spooled = True
                    else:
                        if task._spool_drain is not None:
                            task._spool_drain.forget(task.spec.task_id)
                        task._spool.discard(task.spec.task_id)
                except Exception:
                    log.warning(
                        "node=%s spool seal failed for %s",
                        self.node_id, task.spec.task_id, exc_info=True,
                    )
                    try:
                        task._spool.discard(task.spec.task_id)
                    except Exception:
                        pass
            # in-slice exchange segment: seal BEFORE the terminal state
            # is visible (FINISHED implies the device copy is complete,
            # the spool-commit ordering). A DRAINING worker immediately
            # degrades its ICI edges to HTTP — consumers that have not
            # taken their partition yet fall back to the wire
            if (
                task.spec.ici_slice
                and task.spec.ici_slice == self.slice_id
                and (
                    task.spec.n_partitions > 1
                    or getattr(task, "_ici_gather", False)
                )
            ):
                # gather (single-partition) tasks seal only when their
                # output actually rode the ICI lane: sealing an empty
                # entry while real pages sit in the serialized buffer
                # would read as 'complete, zero rows' to the
                # coordinator's in-slice gather
                try:
                    if outcome == "FINISHED" and task.state != "ABORTED":
                        exchange_spi.seal_task(
                            self.slice_id,
                            task.spec.task_id,
                            max(task.spec.n_partitions, 1),
                        )
                        if self._draining:
                            self._materialize_ici(task)
                    else:
                        freed = exchange_spi.discard_task(
                            task.spec.task_id
                        )
                        if freed:
                            self.memory_pool.release(task.buf_key, freed)
                except Exception:
                    log.warning(
                        "node=%s ici seal failed for %s",
                        self.node_id, task.spec.task_id, exc_info=True,
                    )
            # publish the terminal state LAST: it flips X-Complete on
            # the result stream, and the coordinator reads the final
            # status (stats + spans above) as soon as it sees it
            with task.cond:
                if task.state != "ABORTED":
                    task.state = outcome
                task.cond.notify_all()
            log.info(
                "trace=%s task=%s node=%s state=%s wall_ms=%.1f",
                trace_id, task.spec.task_id, self.node_id,
                task.state, task.stats.wall_ms,
            )
            # unpin replicated/whole-table cache entries this task
            # used, then free its batch-staging reservations
            self.runner.release_pins(task.stats)
            self.memory_pool.release(task.spec.query_id)

    def _execute(self, task: _Task) -> None:
        """Stream split batches of the partitioned scan through the
        compiled fragment (reference: split parallelism — drivers pull
        split batches through the pipeline, SURVEY.md §2.4). Per-batch
        outputs are partial states the coordinator's FINAL step merges,
        so batching is semantics-preserving; it also bounds device
        residency to one batch (the grouped-execution memory shape).
        ``task_concurrency`` drivers overlap host staging with device
        execution."""
        # chaos hook: an armed fault plane may delay this task, fail it
        # (kill_task), or crash the whole worker (kill_worker) here —
        # mid-execute from the coordinator's point of view, since the
        # task POST was already acked
        faults.maybe_inject_task(
            self.node_id, task.spec.task_id, kill=self._fault_kill,
            preempt=self._fault_preempt,
        )
        spec = task.spec
        if spec.sources or spec.partition_scan < 0:
            # merge task: static sources (barrier mode) or dynamically
            # attached ones (pipelined shuffle; partition_scan=-1)
            return self._execute_merge(task)
        root = spec.fragment
        # a pushed-down root sort (ordered MERGE exchange: coordinator
        # wraps the fragment in a SortNode so every emitted batch is a
        # sorted run) executes host-side per batch — the same
        # host-root-stage discipline that keeps XLA sort compiles out of
        # the per-query budget (exec.host_ops)
        from presto_tpu.exec.host_ops import apply_host_ops, peel_host_ops

        root, pushed_ops = peel_host_ops(root)
        scans = [n for n in N.walk(root) if isinstance(n, N.TableScanNode)]
        walk_ids = {
            id(n): i for i, n in enumerate(N.walk(root))
        }
        part_scan = None
        repl_pages = {}
        for s in scans:
            if walk_ids[id(s)] == spec.partition_scan:
                part_scan = s
            else:
                repl_pages[id(s)] = self.runner._load_table(s)

        total = spec.split_end - spec.split_start
        batch = spec.split_batch_rows or max(total, 1)
        ranges = [
            (lo, min(lo + batch, spec.split_end))
            for lo in range(spec.split_start, spec.split_end, batch)
        ] or [(spec.split_start, spec.split_end)]

        def stage_batch(lo: int, hi: int):
            """Stage the partitioned scan's [lo, hi) batch through the
            device-resident split cache (LocalQueryRunner.stage_split:
            one fixed capacity bucket per batch size, so every full
            batch reuses one compiled program; uncached batches
            reserve their live residency under the query, cached ones
            are pinned against eviction until released)."""
            # staging may run on a prefetch/pool thread: point it at
            # the task's stats sink (thread-local on the runner)
            self.runner._qs_local.value = task.stats
            fetched = []

            def read_range():
                fetched.append(True)
                return self._load_range(part_scan, lo, hi)

            page, release = self.runner.stage_split(
                part_scan, lo, hi, bucket_capacity(hi - lo),
                owner=spec.query_id,
                page_source=read_range,
            )
            # one accounting unit (data + validity + offsets), same as
            # the pool reservation stage_split made
            staged_bytes = page_nbytes(page)
            # task.cond guards the stats accumulators: with
            # task_concurrency > 1 concurrent drivers race the
            # read-modify-write (+=) and would drop updates
            with task.cond:
                task.stats.input_rows += hi - lo
                task.stats.input_bytes += staged_bytes
            if fetched:
                # only REAL staging traffic counts — a cache hit moved
                # zero bytes host->device
                REGISTRY.distribution("worker.staging_bytes").add(
                    staged_bytes
                )
            return page, release

        def exec_batch(split_page, release):
            pages = [
                split_page if s is part_scan else repl_pages[id(s)]
                for s in scans
            ]
            t_exec = time.perf_counter()
            try:
                out = self.runner._run_with_pages(root, scans, pages)
                if pushed_ops:
                    out = apply_host_ops(out, pushed_ops)
                return out
            finally:
                with task.cond:
                    task.stats.execute_ms += (
                        time.perf_counter() - t_exec
                    ) * 1000.0
                release()

        def run_batch(lo: int, hi: int):
            self.runner._qs_local.value = task.stats
            page, release = stage_batch(lo, hi)
            return exec_batch(page, release)

        # dynamic-filter SUMMARY task: batch outputs fold into one
        # per-key summary (exec/dynfilter.py — min/max + NDV-capped
        # distinct sets, string keys resolved through the page
        # dictionary) instead of crossing the wire as pages; the
        # coordinator reads the merged summary off the status response
        summary_cell: List = []

        def emit(out) -> None:
            if spec.dynfilter_keys:
                from presto_tpu.exec import dynfilter

                s = dynfilter.summarize_page(
                    out,
                    list(spec.dynfilter_keys),
                    ndv_limit=spec.dynfilter_ndv
                    or dynfilter.DEFAULT_NDV_LIMIT,
                )
                with task.cond:
                    summary_cell.append(s)
                return
            if spec.n_partitions > 1:
                # partitioned output rides the unified exchange SPI:
                # the scheduler-chosen transport (device-resident ICI
                # publish for in-slice stages, serialized HTTP buffers
                # otherwise), spool tee included
                return exchange_spi.emit_partitioned(
                    task, out,
                    slice_id=self.slice_id, pool=self.memory_pool,
                    fold=self.runner._fold_device_stat,
                )
            self._emit_result(task, out)

        def finish_summary() -> None:
            """Merge per-batch summaries into the task's one summary
            (empty range = empty build: nothing can match)."""
            if not spec.dynfilter_keys:
                return
            from presto_tpu.exec import dynfilter

            ndv = spec.dynfilter_ndv or dynfilter.DEFAULT_NDV_LIMIT
            merged = None
            for s in summary_cell:
                merged = s if merged is None else merged.merge(s, ndv)
            if merged is None:
                merged = dynfilter.empty_summary(spec.dynfilter_keys)
            task.dynfilter = merged.to_json()

        if spec.task_concurrency <= 1 or len(ranges) <= 1:
            # pipelined prefetch staging (staging_prefetch_depth /
            # tier-1 staging.prefetch-depth): a background host thread
            # stages split N+1 while the jitted fragment for split N
            # runs on device — compute and transfer overlap instead of
            # alternating. Depth 0 is the exact serial path. The
            # coordinator ships the client session's value on the spec
            # (like page_capacity / task_concurrency); -1 = unset
            depth = (
                spec.prefetch_depth
                if spec.prefetch_depth >= 0
                else int(
                    self.runner.session.get("staging_prefetch_depth")
                )
            )
            from presto_tpu.exec.staging import prefetch_iter

            def staged_ahead(rng):
                t0 = time.perf_counter()
                page, release = stage_batch(*rng)
                if depth > 0:
                    with task.cond:
                        task.stats.prefetch_ms += (
                            time.perf_counter() - t0
                        ) * 1000.0
                return page, release

            def drop_staged(entry):
                # a prefetched-but-never-executed batch surrenders its
                # residency (pool reservation or cache pin) — the task
                # is failing/aborting and the task-end release-all has
                # not run yet (prefetch_iter's abandonment contract)
                entry[1]()

            batches = prefetch_iter(
                ranges, staged_ahead, depth, on_drop=drop_staged
            )
            try:
                for page, release in batches:
                    emit(exec_batch(page, release))
            finally:
                # deterministic close: joins the prefetch thread and
                # drops queued batches BEFORE _run_task's release-all
                batches.close()
            finish_summary()
            return
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(spec.task_concurrency) as pool:
            futs = [pool.submit(run_batch, lo, hi) for lo, hi in ranges]
            for f in futs:
                emit(f.result())
        finish_summary()

    def _emit_result(self, task: "_Task", out) -> None:
        """Root-stage (single-partition) result emit: when the
        coordinator's gather is co-located and the single-program gate
        is on, the output page stays device-resident — the final
        gather becomes one more ICI edge. Everything else keeps the
        serialized chunk-and-offer buffer, and an HTTP puller of an
        ICI-published task still sees real pages through the lazy
        materialize in the results handler."""
        if (
            task.spec.ici_slice
            and self.single_program
            and exchange_spi.emit_gather(
                task, out,
                slice_id=self.slice_id, pool=self.memory_pool,
                fold=self.runner._fold_device_stat,
            )
        ):
            # seal-eligibility latch: only a task whose output rode
            # the ICI lane may seal at FINISH (see _run_task)
            task._ici_gather = True
            return
        cols, n = pages_wire.page_to_wire_columns(out)
        _offer_chunked(task, cols, n)

    def _ici_probe(self, uri: str, src_task: str):
        """Liveness probe for the in-slice fetch wait: is the producer
        attempt still working toward a seal? Control-plane only (one
        tiny status GET between waits); any doubt answers False and
        the consumer degrades to the wire, which has its own retry
        discipline."""
        def probe():
            try:
                st = rpc.call_json(
                    "GET", f"{uri}/v1/task/{src_task}/status",
                    policy=rpc.RpcPolicy(timeout_s=2.0, retries=0),
                )
                return st.get("state") in ("QUEUED", "RUNNING")
            except Exception:
                return False

        return probe

    def _merge_group_page(self, task: "_Task", entries, rschema):
        """Resolve one merge group's tagged transport entries into the
        RemoteSource leaf's input: an all-ICI group merges ON DEVICE —
        first through the stage's single collective program
        (``exchange_spi.collective_merge``: ONE shard_map/all_to_all
        dispatch shared by every partition of the stage), falling open
        to the per-source ``exchange_spi.device_merge`` gather when
        the collective trace is unavailable (same union dictionary,
        row order, and capacity bucket either way, so the fragment
        compiles and computes identically); a mixed or oversized group
        degrades to host payloads, with the ICI sources' share still
        spliced out of the collective program when possible. Returns
        ``(page, None)`` for the device lane or ``(None, payloads)``
        for the legacy host lanes."""
        max_rows = int(self.runner.session.get("max_device_rows"))
        fold = self.runner._fold_device_stat
        ici_srcs = tuple(s for k, _, s in entries if k == "ici")
        if entries and len(ici_srcs) == len(entries):
            res = None
            if self.single_program:
                try:
                    res = exchange_spi.collective_merge(
                        self.slice_id,
                        ici_srcs,
                        [b for _, b, _ in entries],
                        task.spec.partition,
                        rschema,
                        task.spec.n_partitions,
                        max_rows=max_rows,
                        fold=fold,
                    )
                except Exception:
                    REGISTRY.counter(
                        "exchange.collective_fallbacks"
                    ).update()
                    log.warning(
                        "node=%s collective merge failed; degrading "
                        "to per-source gather", self.node_id,
                        exc_info=True,
                    )
                    res = None
            if res is None:
                try:
                    res = exchange_spi.device_merge(
                        [b for _, b, _ in entries],
                        task.spec.partition,
                        rschema,
                        max_rows=max_rows,
                        fold=fold,
                    )
                except Exception:
                    REGISTRY.counter(
                        "exchange.ici_merge_errors"
                    ).update()
                    log.warning(
                        "node=%s device merge failed; degrading to "
                        "host merge", self.node_id, exc_info=True,
                    )
                    res = None
            if res is not None:
                page, total = res
                with task.cond:
                    task.stats.input_rows += total
                return page, None
        spliced = None
        if self.single_program and ici_srcs:
            try:
                spliced = exchange_spi.collective_payloads(
                    self.slice_id,
                    ici_srcs,
                    [b for k, b, _ in entries if k == "ici"],
                    task.spec.partition,
                    rschema,
                    task.spec.n_partitions,
                    fold=fold,
                )
            except Exception:
                REGISTRY.counter(
                    "exchange.collective_fallbacks"
                ).update()
                log.warning(
                    "node=%s collective splice failed; per-source "
                    "fallback", self.node_id, exc_info=True,
                )
                spliced = None
        payloads = []
        si = 0
        for kind, val, _src in entries:
            if kind == "http":
                payloads.extend(val)
                continue
            if spliced is not None:
                conv = spliced[si]
                si += 1
            else:
                conv = exchange_spi.ici_batches_to_payloads(
                    val, task.spec.partition, rschema
                )
            with task.cond:
                task.stats.input_rows += sum(n for _, _, n in conv)
            payloads.extend(conv)
        return None, payloads

    def _spool_partition(self, task: "_Task", logical_key: str):
        """Recovery read: one committed attempt's pages for this merge
        task's partition out of the durable spool (None = nothing
        recoverable). The spool serves raw wire frames; deserialization
        and stats attribution happen here, mirroring the HTTP pull."""
        if self.spool is None:
            return None
        raw = self.spool.serve(logical_key, task.spec.partition)
        if raw is None:
            return None
        pages = [pages_wire.deserialize_page(b) for b in raw]
        with task.cond:
            task.stats.spool_pages_served += len(pages)
        log.info(
            "node=%s task=%s re-served %d page(s) of %s[%d] from spool",
            self.node_id, task.spec.task_id, len(pages), logical_key,
            task.spec.partition,
        )
        return pages

    def _load_range(self, scan: N.TableScanNode, lo: int, hi: int):
        conn = self.runner.catalogs.get(scan.handle.catalog)
        split = ConnectorSplit(scan.handle, lo, hi)
        return conn.create_page_source(split, list(scan.columns))

    def _materialize_ici(self, task: "_Task") -> None:
        """Degrade one task's ICI edges to HTTP, exactly once: the
        drain path and the lazy results-handler path both land here,
        and concurrent result pulls block until the serialized buffers
        are complete (a half-materialized buffer must never flip
        X-Complete under a puller). Serialize is the pure half —
        raising there leaves nothing torn and clears the latch for a
        retry; the buffered commit is atomic."""
        with task._ici_mat_lock:
            if task._ici_mat_done:
                return
            frames = exchange_spi.serialize_ici_frames(task)
            if frames is not None:
                exchange_spi.buffer_frames(
                    task, frames, self.memory_pool
                )
            task._ici_mat_done = True
        # a DELETE may have raced the materialize: its release-all can
        # run BEFORE buffer_frames' reservation, and a task no longer
        # registered gets no future DELETE to release it — re-check
        # membership and drop everything if the task is gone (pullers
        # of a deleted task 404 before reaching the buffers)
        with self._lock:
            gone = task.spec.task_id not in self.tasks
        if gone:
            exchange_spi.discard_task(task.spec.task_id)
            task.drop_buffers()

    # ------------------------------------------- merge task (shuffle read)

    def _execute_merge(self, task: "_Task") -> None:
        """Intermediate-stage task: pull this task's output partition
        from every producer task (worker<->worker data plane — the
        reference's ExchangeClient feeding an intermediate stage), merge
        the payloads (dictionary remap included), and run the fragment
        with its RemoteSourceNode leaf bound to the merged page.

        Correctness: producers hash-partition rows by the final
        aggregation's group keys, so every group lands wholly in one
        partition and per-partition FINAL results concatenate."""
        REGISTRY.counter("worker.merge_tasks").update()
        spec = task.spec
        # dynamic source loop (reference: ExchangeClient consuming
        # addExchangeLocations until noMoreLocations): pull every known
        # source's partition — pulls OVERLAP production, since the
        # token loop polls until the producer reports complete — and
        # wait for more until the coordinator marks the set done.
        # A source is (uri, task_id[, group]): group tags map each
        # producer stage to one RemoteSourceNode leaf (a partitioned
        # JOIN stage has two producer stages — group 0 probe, group 1
        # build); untagged sources are group 0.
        #: per-group tagged transport entries, in source order:
        #: ("http", [(payload, schema, nrows), ...]) from the wire or
        #: the spool, ("ici", [(page, dest), ...]) from the in-slice
        #: segment — _merge_group_page resolves them into each
        #: RemoteSource leaf's input page
        by_group: Dict[int, list] = {}
        pulled = set()
        # in-slice transport applies only when the scheduler planned it
        # AND this attempt actually runs on that slice (a retry that
        # landed cross-slice keeps the wire)
        use_ici = bool(spec.ici_slice) and spec.ici_slice == self.slice_id
        # attempt-id dedup (fault-tolerant execution): every attempt of
        # one logical upstream task shares a logical key, and exactly
        # ONE attempt's pages may be consumed — a retried producer and
        # its zombie original must never both contribute rows
        pulled_logical = set()
        #: logical keys whose announced attempt died unreachable with
        #: no spooled copy — a replacement announcement may still heal
        #: them; anything left at loop end is a hard loss
        abandoned: Dict[str, Exception] = {}
        deadline = time.monotonic() + float(
            self.runner.session.get("query_max_run_time_s")
        )
        while True:
            with task.cond:
                pending = [
                    s for s in task.sources if tuple(s) not in pulled
                ]
                if not pending:
                    if task.sources_done:
                        break
                    if task.state == "ABORTED":
                        raise RuntimeError("merge task aborted")
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            "merge task timed out waiting for sources"
                        )
                    task.cond.wait(timeout=0.1)
                    continue
            for src in pending:
                uri, src_task = src[0], src[1]
                group = int(src[2]) if len(src) > 2 else 0
                lk = task_ids.logical_key(src_task)
                if lk in pulled_logical:
                    pulled.add(tuple(src))
                    continue
                t_pull = time.perf_counter()
                if use_ici:
                    # in-slice lane: take this partition straight out
                    # of the producer's device-resident segment entry
                    # (no serialization, no HTTP); a miss — producer
                    # died, drained, or fell back itself — degrades to
                    # the wire below, then to the spool
                    got_ici = exchange_spi.ici_fetch(
                        self.slice_id, spec, src_task, deadline,
                        probe=self._ici_probe(uri, src_task),
                    )
                    if got_ici is not None:
                        by_group.setdefault(group, []).append(
                            ("ici", got_ici, src_task)
                        )
                        task.stats.staging_ms += (
                            time.perf_counter() - t_pull
                        ) * 1000.0
                        task.stats.exchange_ici_edges += 1
                        abandoned.pop(lk, None)
                        pulled.add(tuple(src))
                        pulled_logical.add(lk)
                        continue
                try:
                    got = _pull_partition(
                        uri, src_task, spec.partition,
                        self.runner.session, policy=self._rpc_policy,
                    )
                    task.stats.exchange_http_edges += 1
                except Exception as e:
                    got = (
                        self._spool_partition(task, lk)
                        if spec.spool
                        else None
                    )
                    if got is None:
                        if spec.spool:
                            # recoverable exchange: the coordinator may
                            # announce a replacement attempt of this
                            # logical task — consume that instead
                            abandoned[lk] = e
                            pulled.add(tuple(src))
                            continue
                        raise
                    task.stats.exchange_spool_edges += 1
                abandoned.pop(lk, None)
                by_group.setdefault(group, []).append(
                    ("http", got, src_task)
                )
                task.stats.staging_ms += (
                    time.perf_counter() - t_pull
                ) * 1000.0
                task.stats.input_rows += sum(p[2] for p in got)
                pulled.add(tuple(src))
                pulled_logical.add(lk)
        lost = [lk for lk in abandoned if lk not in pulled_logical]
        if lost:
            # every attempt of these upstream tasks is gone and nothing
            # was spooled/committed: the merge cannot be correct
            raise RuntimeError(
                f"merge task lost upstream partition(s) {lost}: "
                f"{abandoned[lost[0]]}"
            )
        root = spec.fragment
        remotes = [
            n for n in N.walk(root) if isinstance(n, N.RemoteSourceNode)
        ]
        if len(remotes) > 1:
            # multi-source fragment (partitioned join stage): group i
            # feeds the i-th RemoteSourceNode in walk order; each
            # group's entries merge + stage separately (on device when
            # the whole group arrived in-slice), then the fragment
            # runs once over all leaves
            import numpy as np

            pages = []
            for i, r in enumerate(remotes):
                rschema = dict(r.fragment_root.output_schema())
                page, payloads = self._merge_group_page(
                    task, by_group.get(i, []), rschema
                )
                if page is None:
                    if payloads:
                        merged = pages_wire.merge_payloads(
                            payloads, rschema
                        )
                    else:  # no rows from this side in this partition
                        merged = {
                            nm: np.empty(0, t.np_dtype)
                            for nm, t in rschema.items()
                        }
                    page = stage_page(merged, rschema)
                pages.append(page)
            # same accounting as the single-remote path: a too-big
            # (skewed) join partition fails on MemoryPool accounting
            # (kill-largest policy visible), not device OOM
            staged = sum(
                int(b.data.nbytes)
                for pg in pages
                for b in pg.blocks
            )
            self.memory_pool.reserve(spec.query_id, staged)
            task.stats.input_bytes += staged
            t_exec = time.perf_counter()
            try:
                out = self.runner._run_with_pages(root, remotes, pages)
            finally:
                task.stats.execute_ms += (
                    time.perf_counter() - t_exec
                ) * 1000.0
                self.memory_pool.release(spec.query_id, staged)
            self._emit_result(task, out)
            return
        if len(remotes) != 1:
            raise RuntimeError(
                f"merge fragment must have one RemoteSource leaf, "
                f"got {len(remotes)}"
            )
        schema = dict(remotes[0].fragment_root.output_schema())
        page0, payloads = self._merge_group_page(
            task, by_group.get(0, []), schema
        )
        if page0 is not None:
            # all-in-slice merge: the input page was assembled on
            # device (bit-compatible with the wire path's staged page)
            staged = sum(int(b.data.nbytes) for b in page0.blocks)
            self.memory_pool.reserve(spec.query_id, staged)
            task.stats.input_bytes += staged
            t_exec = time.perf_counter()
            try:
                out = self.runner._run_with_pages(root, remotes, [page0])
            finally:
                task.stats.execute_ms += (
                    time.perf_counter() - t_exec
                ) * 1000.0
                self.memory_pool.release(spec.query_id, staged)
            self._emit_result(task, out)
            return
        # same grouped-execution discipline as the coordinator gather:
        # a partition beyond max_device_rows sub-buckets and merges one
        # bucket at a time (or fails under spill_enabled=false) instead
        # of staging one oversized page
        from presto_tpu.exec import streaming as S

        out = S.grouped_final_merge(
            self.runner,
            payloads,
            schema,
            root,
            remotes[0].fragment_root,
            int(self.runner.session.get("max_device_rows")),
        )
        if out is None:
            merged = pages_wire.merge_payloads(payloads, schema)
            page = stage_page(merged, schema)
            staged = sum(int(b.data.nbytes) for b in page.blocks)
            self.memory_pool.reserve(spec.query_id, staged)
            task.stats.input_bytes += staged
            t_exec = time.perf_counter()
            try:
                out = self.runner._run_with_pages(root, remotes, [page])
            finally:
                task.stats.execute_ms += (
                    time.perf_counter() - t_exec
                ) * 1000.0
                self.memory_pool.release(spec.query_id, staged)
        self._emit_result(task, out)

    # ------------------------------------------------------------- status

    def status(self) -> dict:
        with self._lock:
            if self._shutting_down:
                state = "SHUTTING_DOWN"
            elif self._draining:
                state = "DRAINING"
            else:
                state = "ACTIVE"
            tasks = {tid: t.state for tid, t in self.tasks.items()}
        return {
            "node_id": self.node_id,
            "state": state,
            "uri": self.uri,
            "preemptible": self.preemptible,
            "slice_id": self.slice_id,
            "tasks": tasks,
            "memory": self._memory_report(),
            "backend_diag": devicediag.last_diag_dict(),
        }

    def delete_task(self, task_id: str) -> bool:
        """The one task-teardown primitive (the DELETE route and the
        cluster memory manager's abort both ride it): drop the task,
        abort its execution, free its buffered bytes."""
        with self._lock:
            t = self.tasks.pop(task_id, None)
        if t is None:
            return False
        t.abort()
        # in-slice segment entries die with the task (shuffle
        # partitions must not outlive the query on any worker); the
        # full buf-key release below covers their reservation
        exchange_spi.discard_task(task_id)
        t.drop_buffers()
        return True

    def abort_query(self, query_id: str) -> int:
        """Cluster-wide cancellation, worker side (the low-memory
        killer's ``PUT /v1/memory/abort``): tear down every task of
        the victim through the task-DELETE path and fail its blocked
        reservations — WITHOUT poisoning the query id, so a
        ``retry_policy=QUERY`` re-admission can reserve again."""
        with self._lock:
            doomed = [
                tid
                for tid, t in self.tasks.items()
                if t.spec.query_id == query_id
            ]
        n = 0
        for tid in doomed:
            if self.delete_task(tid):
                n += 1
        self.memory_pool.cancel_blocked(query_id)
        if n:
            log.warning(
                "node=%s memory manager aborted %d task(s) of %s",
                self.node_id, n, query_id,
            )
        return n


def _pull_partition(
    uri: str, src_task: str, part: int, session,
    policy: rpc.RpcPolicy = rpc.DEFAULT_POLICY,
):
    """Token-acked pull of one output partition from a peer worker:
    the shared rpc.pull_pages loop (exchange client, worker side).
    Pulls are idempotent (token-acked), so transient peer failures
    retry under the RPC policy."""
    return rpc.pull_pages(
        uri, src_task, part,
        policy=policy,
        deadline_s=float(session.get("query_max_run_time_s")),
        timeout_msg=f"shuffle pull of {src_task}[{part}] timed out",
    )


def _make_handler(worker: WorkerServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet
            pass

        def _json(self, code: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_body(self) -> bytes:
            n = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(n)

        def do_GET(self):
            parts = [p for p in self.path.split("/") if p]
            if parts == ["v1", "status"]:
                return self._json(200, worker.status())
            if parts == ["v1", "metrics"]:
                body = REGISTRY.render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if len(parts) == 4 and parts[:2] == ["v1", "task"] and parts[3] == "status":
                t = worker.tasks.get(parts[2])
                if t is None:
                    return self._json(404, {"error": "no such task"})
                return self._json(
                    200,
                    {
                        "task_id": parts[2],
                        "state": t.state,
                        "error": t.error,
                        "num_pages": len(t.pages),
                        # durable-copy flag: a FINISHED+spooled task's
                        # output outlives this worker (drain protocol;
                        # QoS suspend-progress accounting reads it too)
                        "spooled": t.spooled,
                        "stats": t.stats.to_dict(),
                        "spans": t.spans,
                        "dynamic_filter": t.dynfilter,
                    },
                )
            if (
                len(parts) == 6
                and parts[:2] == ["v1", "task"]
                and parts[3] == "results"
            ):
                # /v1/task/{id}/results/{buffer}/{token}
                t = worker.tasks.get(parts[2])
                if t is None:
                    return self._json(404, {"error": "no such task"})
                part = int(parts[4])
                token = int(parts[5])
                if t.state == "FAILED":
                    return self._json(500, {"error": t.error})
                if not (0 <= part < len(t.parts)):
                    return self._json(
                        400, {"error": f"no output buffer {part}"}
                    )
                # pulling token N acks pages < N (frees buffer slots and
                # unblocks the producer — the reference's token-advance
                # ack). A pipelined client sends an explicit X-Ack floor
                # instead: its speculative in-flight request for token
                # N+k must NOT free pages it hasn't consumed yet.
                ack_hdr = self.headers.get("X-Ack")
                t.ack_below(
                    int(ack_hdr) if ack_hdr is not None else token,
                    part,
                )
                # snapshot (page, count, state) ATOMICALLY: reading
                # len(pages) then state unlocked races the producer's
                # final append + FINISHED publish — a 204 with
                # X-Complete=true would silently drop the last page
                # (pipelined pulls keep a beyond-the-end token in
                # flight, so the race window is hit on every pull).
                # Lazy ICI degrade rides the SAME snapshot: a wire
                # pull of a FINISHED in-slice task (a merge retry
                # that landed cross-slice) must see the real pages —
                # an ICI task's serialized buffers are empty until
                # materialized, and FINISHED + empty would read as a
                # complete zero-row partition (silent data loss). The
                # FINISHED decision and the materialize check happen
                # on the LOCKED state, then the snapshot re-runs: a
                # producer publishing FINISHED between an unlocked
                # pre-check and the snapshot can never slip through.
                while True:
                    with t.cond:
                        pages = t.parts[part]
                        body = (
                            pages[token] if token < len(pages) else None
                        )
                        n_pages = len(pages)
                        state = t.state
                        complete = state == "FINISHED" and (
                            token + (1 if body is not None else 0)
                            >= n_pages
                        )
                        need_mat = (
                            state == "FINISHED"
                            and bool(t.spec.ici_slice)
                            and not t._ici_mat_done
                        )
                        if complete and not need_mat:
                            # drain protocol: this consumer has seen
                            # the whole stream — the buffer no longer
                            # pins a draining worker alive
                            t.complete_served[part] = True
                    if not need_mat:
                        break
                    worker._materialize_ici(t)
                if body is not None:
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/x-presto-tpu-page"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.send_header("X-Next-Token", str(token + 1))
                    self.send_header(
                        "X-Complete", "true" if complete else "false"
                    )
                    self.end_headers()
                    self.wfile.write(body)
                    return
                # no page at this token yet
                self.send_response(204)
                self.send_header("Content-Length", "0")
                self.send_header("X-Next-Token", str(token))
                self.send_header(
                    "X-Complete", "true" if complete else "false"
                )
                self.end_headers()
                return
            self._json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            parts = [p for p in self.path.split("/") if p]
            if parts == ["v1", "task"]:
                if worker._draining or worker._shutting_down:
                    # reject BEFORE parsing: 503 tells the coordinator
                    # to reschedule on another worker (no task was
                    # created here)
                    return self._json(
                        503, {"error": "worker is draining"}
                    )
                try:
                    spec = FragmentSpec.from_json(
                        json.loads(self._read_body().decode())
                    )
                    # honor the propagated trace context: a header on
                    # the POST covers specs from span-unaware clients
                    hdr = self.headers.get("traceparent", "")
                    if hdr and not spec.traceparent:
                        import dataclasses as _dc

                        spec = _dc.replace(spec, traceparent=hdr)
                    tid = worker.create_task(spec)
                    return self._json(200, {"task_id": tid})
                except WorkerDraining as e:
                    return self._json(503, {"error": str(e)})
                except Exception as e:
                    return self._json(400, {"error": str(e)})
            self._json(404, {"error": f"no route {self.path}"})

        def do_DELETE(self):
            parts = [p for p in self.path.split("/") if p]
            if len(parts) == 3 and parts[:2] == ["v1", "task"]:
                worker.delete_task(parts[2])
                return self._json(200, {"ok": True})
            self._json(404, {"error": f"no route {self.path}"})

        def do_PUT(self):
            parts = [p for p in self.path.split("/") if p]
            if parts == ["v1", "state", "shutdown"]:
                threading.Thread(
                    target=worker.shutdown, daemon=True
                ).start()
                return self._json(200, {"ok": True})
            if parts == ["v1", "memory", "abort"]:
                # cluster memory manager kill, worker side: tear down
                # the victim's tasks (task-DELETE path) and fail its
                # blocked reservations
                body = json.loads(self._read_body() or b"{}")
                qid = body.get("query_id", "")
                if not qid:
                    return self._json(400, {"error": "query_id required"})
                return self._json(
                    200, {"ok": True, "aborted": worker.abort_query(qid)}
                )
            if parts == ["v1", "state", "drain"]:
                # graceful drain: stop accepting, finish + serve/spool
                # running outputs, announce DRAINING, exit clean
                threading.Thread(
                    target=worker.drain, daemon=True
                ).start()
                return self._json(200, {"ok": True, "state": "DRAINING"})
            if (
                len(parts) == 4
                and parts[:2] == ["v1", "task"]
                and parts[3] == "sources"
            ):
                # pipelined shuffle: attach upstream sources to a merge
                # task (reference: addExchangeLocations)
                t = worker.tasks.get(parts[2])
                if t is None:
                    return self._json(404, {"error": "no such task"})
                body = json.loads(self._read_body() or b"{}")
                t.add_sources(
                    body.get("sources", ()), bool(body.get("done"))
                )
                return self._json(200, {"ok": True})
            self._json(404, {"error": f"no route {self.path}"})

    return Handler
