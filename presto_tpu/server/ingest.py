"""Streaming ingest lane: WAL'd micro-batch commits with snapshot
reads.

Reference parity: the append-oriented half of a streaming warehouse —
writers land row micro-batches in a durable per-table write-ahead log,
a commit loop folds them into immutable snapshot versions
(Iceberg-style snapshot-committed tables, the declared SPI long-tail
of COMPONENTS.md §2.2), and readers pin a snapshot per plan so a long
scan never sees a torn batch and is isolated from concurrent appends.
The third durable-log sibling of the coordinator journal
(``server/journal.py``) and the exchange spool (``server/spool.py``),
reusing their proven crc32-framed JSONL idiom.

On-disk shape (one directory, ``ingest.wal-path``): one WAL per table,
``wal-{catalog}.{schema}.{table}.jsonl``, plus ``mviews.jsonl`` for
durable materialized-view definitions. Every line is a checksummed
frame::

    {crc32-of-payload as 8 hex chars} {payload JSON}

Frames: ``schema`` (the table's columns+types, written once so replay
can recreate the table in the volatile memory connector), ``batch``
(one appended micro-batch under a per-table monotone ``seq``), and
``commit`` (``upto`` = the last folded seq; its value also MINTS the
snapshot id — the commit frame is the durability point, so snapshot
ids are born durable). Crash recovery replays each WAL: batches with
``seq <= upto`` of the last commit frame rebuild the committed
snapshot; the uncommitted tail past it is re-admitted as pending
EXACTLY once (its batch frames are already on disk — the next commit
only adds the commit frame); torn/corrupt lines are counted
(``ingest.wal_corrupt``) and skipped — the lane must always come up.

Frame construction/parsing and snapshot-id minting are confined to
this module (``tools/analyze.py`` ``ingest-frames`` rule) — an ad-hoc
frame writer or a second id minter elsewhere would silently break
replay or snapshot isolation.

Commit pipeline (``ingest.commit-interval-ms`` loop, or an explicit
``flush()``): write the commit frame (durability point) -> fold the
delta into the connector (``commit_snapshot``) -> invalidate staged
pages + cached plans of the table -> hand the delta to the
materialized-view registry, which merges it through the existing
aggregation plane (``exec/mview.py``). ``ingest.wal-path`` unset means
none of this constructs — the legacy INSERT/CTAS write path is
bit-exact pre-PR.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from presto_tpu import types as T
from presto_tpu.connectors.spi import ConnectorSplit, TableHandle
from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY

log = logging.getLogger("presto_tpu.ingest")

_WAL_PREFIX = "wal-"
_WAL_SUFFIX = ".jsonl"
_MVIEWS_FILE = "mviews.jsonl"

#: default commit-loop cadence (ingest.commit-interval-ms)
DEFAULT_COMMIT_INTERVAL_MS = 50.0


class IngestError(RuntimeError):
    pass


def _wal_frame(payload: str) -> str:
    """One checksummed WAL frame (the journal/spool idiom): crc32 of
    the UTF-8 payload, then the payload. Verified at replay — a torn
    write truncates the line and fails the check."""
    return f"{zlib.crc32(payload.encode()) & 0xFFFFFFFF:08x} {payload}"


def _parse_wal_line(line: str) -> Optional[dict]:
    """Frame -> record dict, or None for torn/corrupt/foreign lines."""
    line = line.strip()
    if not line:
        return None
    crc_hex, sep, payload = line.partition(" ")
    if not sep or len(crc_hex) != 8:
        return None
    try:
        crc = int(crc_hex, 16)
    except ValueError:
        return None
    if zlib.crc32(payload.encode()) & 0xFFFFFFFF != crc:
        return None
    try:
        rec = json.loads(payload)
    except Exception:
        return None
    return rec if isinstance(rec, dict) and "ev" in rec else None


def _coerce_value(v, dtype):
    """One JSON-decoded WAL/API value -> the engine-native python value
    for ``dtype`` (dates and decimals ride the wire as strings)."""
    if v is None:
        return None
    name = dtype.name
    if getattr(dtype, "is_decimal", False):
        from decimal import Decimal

        return v if isinstance(v, Decimal) else Decimal(str(v))
    if name == "date":
        import datetime

        if isinstance(v, datetime.date):
            return v
        return datetime.date.fromisoformat(str(v))
    if name == "timestamp":
        import datetime

        if isinstance(v, datetime.datetime):
            return v
        return datetime.datetime.fromisoformat(str(v))
    if name in ("bigint", "integer", "smallint", "tinyint"):
        return int(v)
    if name in ("double", "real"):
        return float(v)
    if name == "boolean":
        return bool(v)
    if name.startswith(("varchar", "char")):
        return str(v)
    return v


class _TableLane:
    """Per-table ingest state: the WAL file, the monotone batch seq,
    and the uncommitted pending tail."""

    def __init__(self, handle: TableHandle, path: str):
        self.handle = handle
        self.path = path
        self.lock = threading.Lock()
        self.seq = 0  #: last appended batch seq
        self.committed = 0  #: last committed seq (== tip snapshot id)
        #: uncommitted (seq, columns-dict, nrows), admission order
        self.pending: List[Tuple[int, dict, int]] = []


class IngestManager:
    """The ingest lane of one runner: durable appends, the commit
    loop, crash replay, and durable materialized-view definitions."""

    def __init__(
        self,
        runner,
        wal_path: str,
        commit_interval_ms: float = DEFAULT_COMMIT_INTERVAL_MS,
        start_thread: bool = True,
        lakehouse_path: Optional[str] = None,
        lakehouse_target_file_bytes: Optional[int] = None,
        lakehouse_compaction_interval_s: float = 0.0,
        lakehouse_compaction_min_files: int = 4,
        lakehouse_orphan_ttl_s: float = 86400.0,
    ):
        self.runner = runner
        self.path = wal_path
        self.commit_interval_ms = float(commit_interval_ms)
        os.makedirs(wal_path, exist_ok=True)
        # the durable lakehouse tee (lakehouse.path): commits publish
        # a manifest snapshot BEFORE the WAL commit frame, so restart
        # restores volatile tables from the manifest tip instead of
        # replaying batch frames. Unset = bit-exact legacy behavior
        # (no store constructed, no compaction thread)
        self.store = None
        self._compact_min_files = int(lakehouse_compaction_min_files)
        self._compact_interval = float(lakehouse_compaction_interval_s)
        self._orphan_ttl = float(lakehouse_orphan_ttl_s)
        if lakehouse_path:
            from presto_tpu.server.manifests import (
                DEFAULT_TARGET_FILE_BYTES,
                ManifestStore,
            )

            self.store = ManifestStore(
                lakehouse_path,
                target_file_bytes=(
                    lakehouse_target_file_bytes
                    or DEFAULT_TARGET_FILE_BYTES
                ),
            )
        #: dotted 3-part name -> lane
        self._lanes: Dict[str, _TableLane] = {}
        self._lanes_mu = threading.Lock()
        #: serializes whole commit passes (the loop vs explicit flush):
        #: commit frames and connector folds must land in seq order
        self._commit_mu = threading.Lock()
        self._mv_mu = threading.Lock()
        self._stop = threading.Event()
        # per-MANAGER tallies (the REGISTRY counters are process-global
        # and survive restarts within one process — stats()/the caches
        # row must report THIS lane, not every lane ever constructed)
        self._n_batches = 0
        self._n_commits = 0
        self._n_replayed = 0
        runner.ingest = self
        self._replay()
        self._thread = None
        self._compact_thread = None
        if start_thread and self.commit_interval_ms > 0:
            self._thread = threading.Thread(
                target=self._commit_loop,
                name="ingest-commit",
                daemon=True,
            )
            self._thread.start()
        if (
            start_thread
            and self.store is not None
            and self._compact_interval > 0
        ):
            self._compact_thread = threading.Thread(
                target=self._compaction_loop,
                name="lakehouse-compaction",
                daemon=True,
            )
            self._compact_thread.start()

    # --------------------------------------------------------- resolve

    def _resolve(self, table) -> Tuple[str, str, str]:
        if isinstance(table, str):
            parts = tuple(p for p in table.split(".") if p)
        else:
            parts = tuple(table)
        sess = self.runner.session
        if len(parts) == 3:
            return parts  # type: ignore[return-value]
        if len(parts) == 2:
            return (sess.catalog, parts[0], parts[1])
        if len(parts) == 1:
            return (sess.catalog, sess.schema, parts[0])
        raise IngestError(f"bad table name {table!r}")

    def _lane(self, handle: TableHandle) -> _TableLane:
        dotted = ".".join(handle.table_key)
        with self._lanes_mu:
            lane = self._lanes.get(dotted)
            if lane is None:
                lane = _TableLane(
                    handle,
                    os.path.join(
                        self.path, f"{_WAL_PREFIX}{dotted}{_WAL_SUFFIX}"
                    ),
                )
                self._lanes[dotted] = lane
            return lane

    # ------------------------------------------------------------ disk

    def _write_frame(self, lane_or_path, *recs: dict) -> None:
        """Append one or more frames in ONE open (caller holds the
        owning lock — on-disk frame order must equal logical order or
        replay diverges)."""
        path = (
            lane_or_path.path
            if isinstance(lane_or_path, _TableLane)
            else lane_or_path
        )
        chunk = "".join(
            _wal_frame(json.dumps(rec, default=str)) + "\n"
            for rec in recs
        )
        faults.maybe_inject_io("write", path)
        with open(path, "a", encoding="utf-8") as f:
            f.write(chunk)
            f.flush()
            # the append is ACKED as durable, so it must BE durable
            # before the ack — flush alone leaves it in the page cache
            faults.maybe_inject_io("fsync", path)
            os.fsync(f.fileno())
        REGISTRY.counter("ingest.wal_bytes").update(len(chunk.encode()))

    # ---------------------------------------------------------- append

    def append(self, table, columns=None, rows=None) -> dict:
        """Durably append one row micro-batch to ``table``'s WAL.
        Accepts columnar ``columns={col: [values]}`` or row-dict
        ``rows=[{col: value}, ...]`` form. The batch is acknowledged
        once framed on disk; it becomes VISIBLE to readers at the next
        commit (snapshot semantics — never a torn batch)."""
        parts = self._resolve(table)
        handle = TableHandle(*parts)
        conn = self.runner.catalogs.get(handle.catalog)
        if not hasattr(conn, "commit_snapshot"):
            raise IngestError(
                f"catalog {handle.catalog} does not support snapshot "
                "commits (ingest needs the snapshot SPI)"
            )
        tschema = conn.metadata().get_table_schema(handle)
        if rows is not None:
            if columns is not None:
                raise IngestError("pass either rows or columns, not both")
            # validate row keys BEFORE projecting onto the schema —
            # r.get(c) would otherwise silently drop a typo'd column
            # (and NULL-fill the real one) with a 200 ack
            seen = set()
            for r in rows:
                seen.update(r)
            unknown = seen - set(tschema)
            if unknown:
                raise IngestError(
                    f"unknown column(s) {sorted(unknown)}"
                )
            missing = set(tschema) - seen
            if missing:
                raise IngestError(
                    f"missing column(s) {sorted(missing)}"
                )
            columns = {
                c: [r.get(c) for r in rows] for c in tschema
            }
        if not columns:
            raise IngestError("empty batch: no rows/columns payload")
        unknown = set(columns) - set(tschema)
        if unknown:
            raise IngestError(f"unknown column(s) {sorted(unknown)}")
        missing = set(tschema) - set(columns)
        if missing:
            raise IngestError(f"missing column(s) {sorted(missing)}")
        lens = {c: len(v) for c, v in columns.items()}
        n = next(iter(lens.values()))
        if any(m != n for m in lens.values()):
            raise IngestError(f"ragged batch: column lengths {lens}")
        if n == 0:
            raise IngestError("empty batch: zero rows")
        coerced = {
            c: [_coerce_value(v, tschema[c]) for v in columns[c]]
            for c in tschema
        }
        lane = self._lane(handle)
        with lane.lock:
            recs = []
            if not os.path.exists(lane.path):
                # first frame of a fresh WAL: the schema, so replay
                # can recreate the table in the volatile store
                recs.append(
                    {
                        "ev": "schema",
                        "table": ".".join(parts),
                        "cols": {
                            c: str(t) for c, t in tschema.items()
                        },
                    }
                )
            lane.seq += 1
            seq = lane.seq
            recs.append({"ev": "batch", "seq": seq, "cols": coerced})
            self._write_frame(lane, *recs)
            lane.pending.append((seq, coerced, n))
            pending = len(lane.pending)
            self._n_batches += 1
        REGISTRY.counter("ingest.batches").update()
        REGISTRY.counter("ingest.rows").update(n)
        return {
            "table": ".".join(parts),
            "seq": seq,
            "rows": n,
            "pending_batches": pending,
        }

    # ---------------------------------------------------------- commit

    def _commit_loop(self) -> None:
        interval = max(self.commit_interval_ms, 1.0) / 1000.0
        while not self._stop.wait(interval):
            try:
                self.commit_tick()
            except Exception:
                log.warning("ingest commit tick failed", exc_info=True)

    def commit_tick(self) -> int:
        """Fold every table's pending tail into a new committed
        snapshot. Returns the number of tables committed."""
        with self._lanes_mu:
            lanes = list(self._lanes.values())
        done = 0
        for lane in lanes:
            if lane.pending and self._flush_lane(lane):
                done += 1
        return done

    def flush(self) -> int:
        """Synchronous commit of everything pending (tests, the
        endpoint's ``commit`` flag, shutdown)."""
        return self.commit_tick()

    def _flush_lane(self, lane: _TableLane) -> bool:
        t0 = time.perf_counter()
        with self._commit_mu:
            with lane.lock:
                if not lane.pending:
                    return False
                batches = lane.pending
                lane.pending = []
                upto = batches[-1][0]
                # sid == the last folded seq, so ids are per-table
                # monotone. Legacy (no lakehouse): the commit frame is
                # the durability point AND the id mint. Lakehouse: the
                # manifest ``_current`` swap below is the durability
                # point — the frame just lets replay skip the tail
                sid = upto
            handle = lane.handle
            conn = self.runner.catalogs.get(handle.catalog)
            tschema = conn.metadata().get_table_schema(handle)
            delta = {
                c: [v for _seq, cols, _n in batches for v in cols[c]]
                for c in tschema
            }
            # durable publish FIRST (manifest-backed tables): a disk
            # failure at ANY stage leaves the old tip reachable — the
            # batches go back to the pending front and the whole
            # commit retries cleanly. The acked WAL frames are
            # untouched either way: never an acked-batch loss
            published = folded = False
            try:
                published, folded = self._publish_durable(
                    handle, conn, tschema, delta, sid
                )
            except (OSError, RuntimeError):
                REGISTRY.counter("lakehouse.commit_retries").update()
                log.warning(
                    "lakehouse publish of %s@%s failed — commit will "
                    "retry", ".".join(handle.table_key), sid,
                    exc_info=True,
                )
                with lane.lock:
                    lane.pending = batches + lane.pending
                return False
            try:
                with lane.lock:
                    self._write_frame(
                        lane,
                        {"ev": "commit", "upto": upto, "snapshot": sid},
                    )
                    lane.committed = upto
            except OSError:
                if not published:
                    # legacy mode: the frame WAS the durability point
                    # — nothing committed, retry the whole batch set
                    with lane.lock:
                        lane.pending = batches + lane.pending
                    return False
                # the manifest tip is durable; replay reconciles
                # ``committed = max(wal upto, manifest tip)`` without
                # the frame, so the commit stands
                with lane.lock:
                    lane.committed = upto
                log.warning(
                    "WAL commit frame for %s@%s lost (manifest tip "
                    "carries the commit)", ".".join(handle.table_key),
                    sid, exc_info=True,
                )
            if not folded:
                # fold the delta into the connector for visibility —
                # the lakehouse tee above was durability only (native
                # manifest connectors already folded inside their own
                # commit_snapshot)
                conn.commit_snapshot(handle, delta, sid)
            # drop staged pages + cached plans of every snapshot of
            # the table (and bump the MV staleness epoch through the
            # same audited seam)
            self.runner._invalidate_table_caches(handle)
            # sampled INSIDE the commit mutex, right after this
            # commit's own epoch bump: the registry uses it to
            # attribute the bump to the merged delta — a gap between
            # hint and the view's covered epoch means an interleaved
            # legacy write the delta does not carry
            reg = getattr(self.runner, "_mview_registry", None)
            epoch_hint = (
                reg._epoch(handle) if reg is not None else None
            )
        REGISTRY.counter("ingest.commits").update()
        self._n_commits += 1
        # MV maintenance OUTSIDE the commit mutex: merges are
        # associative+commutative, and holding a lock across device
        # work would stall appends. A maintenance failure must not
        # fail the commit (the data IS committed) — it logs, counts,
        # and the staleness read gate repairs the view on next read
        if reg is not None:
            try:
                reg.on_commit(handle, delta, sid, epoch_hint)
            except Exception:
                REGISTRY.counter("mview.maintenance_errors").update()
                log.warning(
                    "materialized-view maintenance failed for %s@%s",
                    ".".join(handle.table_key), sid, exc_info=True,
                )
        REGISTRY.distribution("ingest.commit_ms").add(
            (time.perf_counter() - t0) * 1000.0
        )
        return True

    # ------------------------------------------------------- lakehouse

    def _publish_durable(
        self, handle, conn, tschema, delta, sid
    ) -> Tuple[bool, bool]:
        """Durably publish one commit's delta as manifest snapshot
        ``sid`` BEFORE the WAL commit frame. Returns ``(published,
        folded)``: native manifest connectors fold visibility inside
        their own ``commit_snapshot`` (folded=True); volatile tables
        tee through the ingest-level store (folded=False); no store
        anywhere = legacy WAL-only commit (False, False). Raises on
        I/O failure — the caller restores the batches and retries."""
        if getattr(conn, "manifest_store", None) is not None:
            conn.commit_snapshot(handle, delta, sid)
            return True, True
        if self.store is None:
            return False, False
        tk = handle.table_key
        if not self.store.has_table(tk):
            # first lakehouse commit of this table: bootstrap the
            # manifest from the connector's live committed rows, so
            # pre-lakehouse history survives the first restart too
            pre = self._connector_rows(conn, handle, tschema)
            if pre is not None and any(
                len(v) for v in pre.values()
            ):
                delta = {
                    c: list(pre.get(c, ())) + list(delta.get(c, ()))
                    for c in tschema
                }
        self.store.commit(tk, tschema, delta, sid)
        return True, False

    def _connector_rows(self, conn, handle, tschema):
        """Full committed contents of a volatile table as python
        values (the manifest bootstrap input); None when unreadable."""
        try:
            nrows = int(
                conn.metadata().get_table_stats(handle).row_count or 0
            )
            if nrows == 0:
                return None
            page = conn.create_page_source(
                ConnectorSplit(handle, 0, nrows), list(tschema)
            )
            return {c: list(page[c]) for c in tschema}
        except Exception:
            log.warning(
                "lakehouse bootstrap read of %s failed",
                ".".join(handle.table_key), exc_info=True,
            )
            return None

    def _restore_from_tip(
        self, conn, handle, store, tip, batches, upto
    ) -> bool:
        """Restart recovery for a manifest-backed volatile table:
        rebuild the committed rows from the durable tip (bit-equal to
        what was committed — parquet round-trips the engine's value
        domain exactly), re-register the snapshot lineage so time
        travel survives the restart, then fold any WAL-only committed
        batches past the tip (commits from before the lakehouse was
        enabled). Returns False to fall back to pure-WAL restore."""
        tk = handle.table_key
        try:
            vals = store.read_values(tk, tip)
        except OSError:
            vals = None
        if vals is None:
            log.warning(
                "lakehouse restore of %s@%s failed — falling back to "
                "WAL replay", ".".join(tk), tip,
            )
            return False
        meta_schema = conn.metadata().get_table_schema(handle)
        conn.commit_snapshot(
            handle, {c: vals.get(c, []) for c in meta_schema}, tip
        )
        restore = getattr(conn, "restore_snapshots", None)
        if restore is not None:
            pairs = []
            for s in store.sids(tk):
                m = store.manifest(tk, s)
                if m is not None:
                    pairs.append((s, m.row_count))
            restore(handle, pairs)
        extra = [
            (s, batches[s]) for s in sorted(batches) if tip < s <= upto
        ]
        if extra:
            delta = {
                c: [
                    _coerce_value(v, meta_schema[c])
                    for _s, cols in extra
                    for v in cols.get(c, ())
                ]
                for c in meta_schema
            }
            conn.commit_snapshot(handle, delta, upto)
        REGISTRY.counter("lakehouse.restores").update()
        return True

    def _compaction_loop(self) -> None:
        interval = max(self._compact_interval, 0.05)
        while not self._stop.wait(interval):
            try:
                self.compaction_tick()
            except Exception:
                log.warning(
                    "lakehouse compaction tick failed", exc_info=True
                )

    def compaction_tick(self, force: bool = False) -> int:
        """Rewrite small commit files into ~target-file-bytes chunks,
        one new snapshot per table — background housekeeping that
        DEFERS to foreground queries (PR 13's low-priority lane:
        while any QoS lane has queued work, the tick yields). Also
        runs the TTL'd orphan GC. Returns tables compacted."""
        if self.store is None:
            return 0
        cluster = getattr(self.runner, "cluster", None)
        qos = getattr(cluster, "qos", None) if cluster else None
        if qos is not None and not force:
            idle = getattr(qos, "background_idle", None)
            if idle is not None and not idle():
                REGISTRY.counter(
                    "lakehouse.compaction_deferred"
                ).update()
                return 0
        with self._lanes_mu:
            lanes = list(self._lanes.values())
        done = 0
        for lane in lanes:
            handle = lane.handle
            try:
                conn = self.runner.catalogs.get(handle.catalog)
            except KeyError:
                continue
            store = getattr(conn, "manifest_store", None) or self.store
            tk = handle.table_key
            if not store.has_table(tk):
                continue
            with self._commit_mu:
                with lane.lock:
                    if lane.pending:
                        continue  # commit the tail first
                    # mint the compaction snapshot id from the lane's
                    # seq space (id minting stays confined here); a
                    # no-op tick just leaves a gap, which monotone
                    # per-table ids tolerate by design
                    lane.seq += 1
                    sid = lane.seq
                try:
                    m = store.compact(
                        tk, sid, min_files=self._compact_min_files
                    )
                except (OSError, RuntimeError):
                    log.warning(
                        "lakehouse compaction of %s failed",
                        ".".join(tk), exc_info=True,
                    )
                    continue
                if m is None:
                    continue
                if getattr(conn, "manifest_store", None) is None:
                    # register the compaction snapshot in the volatile
                    # store's history (empty delta: same rows, new id)
                    # so FOR VERSION AS OF the compacted snapshot pins
                    tschema = conn.metadata().get_table_schema(handle)
                    conn.commit_snapshot(
                        handle, {c: [] for c in tschema}, sid
                    )
                done += 1
            # pinned readers keep serving the old files — only the
            # TTL'd GC reclaims compacted-away snapshots
            self.runner._invalidate_table_caches(handle)
        if self._orphan_ttl > 0:
            try:
                self.store.gc_orphans(self._orphan_ttl)
            except OSError:
                pass
        return done

    # ------------------------------------------------ materialized views

    def record_mview(self, name: str, sql: str) -> None:
        """Durably record one CREATE MATERIALIZED VIEW (replay
        re-registers it before refreshing over the rebuilt base)."""
        with self._mv_mu:
            self._write_frame(
                os.path.join(self.path, _MVIEWS_FILE),
                {"ev": "mview", "name": name, "sql": sql},
            )

    def record_mview_drop(self, name: str) -> None:
        with self._mv_mu:
            self._write_frame(
                os.path.join(self.path, _MVIEWS_FILE),
                {"ev": "mview_drop", "name": name},
            )

    # ---------------------------------------------------------- replay

    def _wal_files(self) -> List[str]:
        try:
            names = sorted(
                f
                for f in os.listdir(self.path)
                if f.startswith(_WAL_PREFIX) and f.endswith(_WAL_SUFFIX)
            )
        except OSError:
            return []
        return [os.path.join(self.path, f) for f in names]

    def _replay(self) -> None:
        """Crash recovery: rebuild each table's committed snapshot from
        its WAL and re-admit the uncommitted tail exactly once, then
        re-register durable materialized views and refresh them over
        the rebuilt bases. Assumes the backing store is the volatile
        memory connector starting empty (a table that ALREADY exists is
        assumed live — its committed rows are not re-applied)."""
        corrupt = 0
        replayed_tail = 0
        for path in self._wal_files():
            tschema_txt: Dict[str, str] = {}
            dotted = os.path.basename(path)[
                len(_WAL_PREFIX):-len(_WAL_SUFFIX)
            ]
            batches: "Dict[int, dict]" = {}
            upto = 0
            sid = 0
            try:
                with open(path, encoding="utf-8") as f:
                    for raw in f:
                        if not raw.strip():
                            continue
                        rec = _parse_wal_line(raw)
                        if rec is None:
                            corrupt += 1
                            continue
                        ev = rec.get("ev")
                        if ev == "schema":
                            tschema_txt = dict(rec.get("cols") or {})
                            dotted = rec.get("table", dotted)
                        elif ev == "batch" and rec.get("seq"):
                            batches[int(rec["seq"])] = (
                                rec.get("cols") or {}
                            )
                        elif ev == "commit":
                            upto = max(upto, int(rec.get("upto", 0)))
                            sid = max(
                                sid, int(rec.get("snapshot", upto))
                            )
            except OSError:
                continue
            # heal the tail boundary: a torn final line has no
            # newline, and the NEXT append would fuse with it into one
            # unparseable frame — losing a GOOD commit/batch frame to
            # a crash that already happened
            try:
                with open(path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    last = f.read(1)
                if last and last != b"\n":
                    with open(path, "a", encoding="utf-8") as f:
                        f.write("\n")
            except OSError:
                pass
            if not tschema_txt and not batches:
                continue
            parts = self._resolve(dotted)
            handle = TableHandle(*parts)
            # the lane's seq/committed watermarks restore BEFORE any
            # catalog-dependent work: even when the data cannot be
            # re-applied, a later append must never reuse a seq an
            # on-disk commit frame already covers (a reused seq makes
            # the NEXT replay promote the wrong batch to committed)
            lane = self._lane(handle)
            lane.seq = max([upto] + list(batches))
            lane.committed = upto
            try:
                conn = self.runner.catalogs.get(handle.catalog)
            except KeyError:
                log.warning(
                    "ingest replay: catalog %s not mounted — %s's "
                    "committed WAL rows were NOT restored (mount "
                    "catalogs before the manager constructs, e.g. "
                    "pass them to CoordinatorServer); seq watermarks "
                    "preserved",
                    handle.catalog, dotted,
                )
                continue
            tschema = {
                c: T.parse_type(t) for c, t in tschema_txt.items()
            }
            # lakehouse reconciliation: the manifest ``_current`` tip
            # may be AHEAD of the last WAL commit frame (a crash hit
            # the window between the durable publish and the frame) —
            # the tip wins, and the watermarks move up so the covered
            # batches are NOT re-admitted (exactly-once tail replay)
            cstore = getattr(conn, "manifest_store", None)
            store = cstore if cstore is not None else self.store
            tk = handle.table_key
            tip = None
            if store is not None and store.has_table(tk):
                tip = store.current_sid(tk)
            if tip is not None:
                lane.seq = max(lane.seq, tip)
                lane.committed = max(upto, tip)
            try:
                existing = handle.table in conn.metadata().list_tables(
                    handle.schema
                )
            except Exception:
                existing = False
            if not existing and tschema:
                conn.create_table(handle, tschema)
            # re-apply committed rows unless the table already exists
            # WITH data (then it is assumed live — a second manager
            # over a live runner must not double-apply). An existing
            # but EMPTY table is the idempotent re-create pattern
            # (embedder re-ran CREATE TABLE before recovery): its
            # committed rows are on disk only, so apply them
            table_rows = 0.0
            if existing:
                try:
                    table_rows = float(
                        conn.metadata()
                        .get_table_stats(handle)
                        .row_count
                        or 0.0
                    )
                except Exception:
                    table_rows = 0.0
            restored_from_tip = False
            if (
                tip is not None
                and cstore is None
                and table_rows == 0.0
            ):
                restored_from_tip = self._restore_from_tip(
                    conn, handle, store, tip, batches, upto
                )
            if (
                upto
                and table_rows == 0.0
                and not restored_from_tip
                and cstore is None
            ):
                committed = [
                    (s, batches[s]) for s in sorted(batches) if s <= upto
                ]
                if committed:
                    meta_schema = conn.metadata().get_table_schema(
                        handle
                    )
                    delta = {
                        c: [
                            _coerce_value(v, meta_schema[c])
                            for _s, cols in committed
                            for v in cols.get(c, ())
                        ]
                        for c in meta_schema
                    }
                    conn.commit_snapshot(handle, delta, sid or upto)
            # the uncommitted tail re-admits EXACTLY once: queued as
            # pending (its batch frames are already on disk — the next
            # commit only adds the commit frame), never applied here
            meta_schema = conn.metadata().get_table_schema(handle)
            for s in sorted(batches):
                if s <= lane.committed:
                    continue
                cols = {
                    c: [
                        _coerce_value(v, meta_schema[c])
                        for v in batches[s].get(c, ())
                    ]
                    for c in meta_schema
                }
                n = (
                    len(next(iter(cols.values()))) if cols else 0
                )
                lane.pending.append((s, cols, n))
                replayed_tail += 1
        if corrupt:
            REGISTRY.counter("ingest.wal_corrupt").update(corrupt)
            log.warning(
                "ingest replay skipped %d corrupt/torn line(s) under %s",
                corrupt, self.path,
            )
        if replayed_tail:
            REGISTRY.counter("ingest.replayed").update(replayed_tail)
            self._n_replayed = replayed_tail
        self._replay_mviews()

    def _replay_mviews(self) -> None:
        path = os.path.join(self.path, _MVIEWS_FILE)
        if not os.path.exists(path):
            return
        live: "Dict[str, str]" = {}
        try:
            with open(path, encoding="utf-8") as f:
                for raw in f:
                    if not raw.strip():
                        continue
                    rec = _parse_wal_line(raw)
                    if rec is None:
                        REGISTRY.counter("ingest.wal_corrupt").update()
                        continue
                    if rec.get("ev") == "mview" and rec.get("name"):
                        live[rec["name"]] = rec.get("sql", "")
                    elif rec.get("ev") == "mview_drop":
                        live.pop(rec.get("name"), None)
        except OSError:
            return
        reg = self.runner.mview_registry
        for name, sql in live.items():
            mv = reg.restore(sql)
            if mv is not None:
                # rebuild state + stored contents from the recovered
                # base — bit-identical to a cold full refresh by
                # construction (it IS one)
                try:
                    reg.refresh_view(mv, mode="replay")
                except Exception:
                    log.warning(
                        "ingest replay: refresh of %s failed", name,
                        exc_info=True,
                    )

    # ----------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lanes_mu:
            lanes = list(self._lanes.values())
        pending_b = sum(len(ln.pending) for ln in lanes)
        pending_r = sum(
            n for ln in lanes for _s, _c, n in ln.pending
        )
        # actual on-disk occupancy of THIS lane's directory — the
        # written-bytes counter is process-global and zero after a
        # restart that wrote nothing yet
        wal_bytes = 0
        for path in self._wal_files() + [
            os.path.join(self.path, _MVIEWS_FILE)
        ]:
            try:
                wal_bytes += os.path.getsize(path)
            except OSError:
                pass
        return {
            "tables": len(lanes),
            "pending_batches": pending_b,
            "pending_rows": pending_r,
            "wal_bytes": wal_bytes,
            "batches": self._n_batches,
            "commits": self._n_commits,
            "replayed": self._n_replayed,
        }

    def close(self, final_flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._compact_thread is not None:
            self._compact_thread.join(timeout=5.0)
        if final_flush:
            try:
                self.commit_tick()
            except Exception:
                log.warning(
                    "ingest final flush failed", exc_info=True
                )
