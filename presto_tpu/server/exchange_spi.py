"""Unified exchange SPI: one producer/consumer surface over the three
shuffle transports.

Reference parity: the exchange layer — ``PartitionedOutputOperator`` /
``OutputBuffer`` on the producer side, ``ExchangeClient`` on the
consumer side (SURVEY.md §2.5). The reference has exactly one data
plane (serialized pages over HTTP); this engine has three, unified
here behind one emit/fetch surface:

- **ICI** (in-slice): co-located workers — one slice, one host process
  driving the device mesh — exchange partitioned output as
  device-resident pages through the :class:`IciSegment`. The producer
  computes per-row destinations in a compiled program
  (``parallel.exchange.bucket_dest``) and the consumer gathers its
  partition straight out of the producers' device pages
  (``parallel.exchange.ici_append``): no host copy, no serialization,
  no zlib, no HTTP — the bytes that would have crossed the wire are
  counted in ``exchange.ici_bytes_elided`` instead.
- **HTTP** (cross-slice / cross-host): the classic serialized page
  wire (``pages_wire`` + token-acked pulls), byte-counted in
  ``exchange.http_shuffle_bytes``.
- **Spool** (recovery): the durable ``ExchangeSpool`` tee under
  ``retry_policy=TASK`` — ICI producers still tee serialized frames so
  a dead in-slice peer's partitions recover exactly like HTTP ones.

Transport *selection* is NOT made here: the scheduler
(``server/scheduler.py``) owns it per stage, and the chosen slice
rides ``FragmentSpec.ici_slice`` (empty = HTTP, the bit-exact legacy
path). This module enforces the contract mechanically: a worker whose
own slice does not match the spec's, a partition fan-out beyond the
kernel bound, or an ineligible page shape falls back to the HTTP lane
and counts ``exchange.ici_fallbacks`` — ICI is an optimization, never
a correctness dependency (a consumer that finds no sealed segment
entry falls back to HTTP, then to the spool, exactly like a dead HTTP
peer today).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from presto_tpu.utils.metrics import REGISTRY
from presto_tpu.utils.telemetry import DEVICE

log = logging.getLogger("presto_tpu.exchange")


def _fetch_dest(dest, nr: int):
    """The ONE destination-vector fetch of the ICI lane (a small
    device->host control transfer per batch), accounted on the
    device-plane telemetry counters."""
    import jax

    arr = np.asarray(jax.device_get(dest))
    DEVICE.count_d2h(int(arr.nbytes))
    return arr[:nr].astype(np.int64)


def _count_dispatch(n: int = 1, fold=None) -> None:
    """Device-program launch accounting for the exchange lane's
    compiled kernels, on the same choke-point counters the fragment
    runner feeds; ``fold`` is the runner's per-query stat folder
    (``_fold_device_stat``) when the caller has one — EXPLAIN ANALYZE's
    per-query ``device.dispatches`` is the proof the single-program
    path dispatches less."""
    DEVICE.count_dispatch(n)
    if fold is not None:
        fold(device_dispatches=n)


def default_slice_id() -> str:
    """Slice identity announced on discovery: co-location means ONE
    host process driving one device mesh (the in-slice segment is
    process-local), so the default identity is platform + pid.
    ``exchange.slice-id`` overrides it for topologies that need an
    explicit name; a wrong override is safe — a cross-process fetch
    misses the segment and falls back to HTTP."""
    import os

    import jax

    try:
        platform = jax.devices()[0].platform
    except Exception:  # backend not initialized: HTTP-only worker
        return ""
    return f"{platform}-{os.getpid()}"


def device_coords() -> list:
    """Device coordinates announced beside the slice id (topology
    observability; the scheduler groups by slice id alone)."""
    import jax

    try:
        return [int(d.id) for d in jax.devices()]
    except Exception:
        return []


# ------------------------------------------------------------ segment


class IciSegment:
    """Process-global registry of device-resident partitioned output.

    One entry per producer task attempt: the raw output pages plus
    their per-row destination arrays, sealed when the task FINISHES
    (mirroring the spool's commit-before-terminal-state ordering, so a
    consumer that observes FINISHED can trust sealed-or-never).
    Entries die with the task: DELETE/abort discards them, drain
    materializes unconsumed partitions to the HTTP buffers first.
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._entries: Dict[str, dict] = {}

    def publish(
        self,
        slice_id: str,
        task_id: str,
        nparts: int,
        page,
        dest,
        nbytes: int,
        on_consumed=None,
    ) -> None:
        with self._cond:
            e = self._entries.get(task_id)
            if e is None:
                e = {
                    "slice": slice_id,
                    "nparts": nparts,
                    "batches": [],
                    "bytes": 0,
                    "sealed": False,
                    "consumed": set(),
                    "on_consumed": on_consumed,
                }
                self._entries[task_id] = e
            e["batches"].append((page, dest))
            e["bytes"] += nbytes
            if on_consumed is not None:
                e["on_consumed"] = on_consumed
            self._cond.notify_all()

    def seal(self, slice_id: str, task_id: str, nparts: int) -> None:
        """Producer finished cleanly: the entry may serve consumers.
        A zero-output producer (empty range, fully-filtered batch)
        never published — sealing creates an empty sealed entry so its
        consumers learn 'complete, zero rows' in-slice instead of
        paying an HTTP round trip to an empty buffer."""
        with self._cond:
            e = self._entries.get(task_id)
            if e is None:
                e = {
                    "slice": slice_id,
                    "nparts": nparts,
                    "batches": [],
                    "bytes": 0,
                    "sealed": True,
                    "consumed": set(),
                    "on_consumed": None,
                }
                self._entries[task_id] = e
            e["sealed"] = True
            self._cond.notify_all()

    def discard(self, task_id: str) -> int:
        """Drop an entry (task failed/aborted/DELETEd or drain
        materialized it); returns the accounted bytes freed so the
        caller can release its pool reservation."""
        with self._cond:
            e = self._entries.pop(task_id, None)
            self._cond.notify_all()
            return e["bytes"] if e is not None else 0

    def peek(self, slice_id: str, task_id: str) -> str:
        """'sealed' | 'open' | 'absent' | 'foreign' (present but
        published under a different slice — a misconfigured override,
        never served)."""
        with self._cond:
            e = self._entries.get(task_id)
            if e is None:
                return "absent"
            if e["slice"] != slice_id:
                return "foreign"
            return "sealed" if e["sealed"] else "open"

    def take(self, slice_id: str, task_id: str, part: int):
        """Consume one partition of a sealed entry: returns the
        ``[(page, dest), ...]`` batch list (device arrays, shared
        immutable) or None. Marks the partition consumed — a draining
        producer knows an ICI consumer already has these rows."""
        with self._cond:
            e = self._entries.get(task_id)
            if e is None or not e["sealed"] or e["slice"] != slice_id:
                return None
            e["consumed"].add(int(part))
            cb = e["on_consumed"]
            batches = list(e["batches"])
        if cb is not None:
            try:
                cb(int(part))
            except Exception:  # consumed-tracking must never fail a read
                pass
        return batches

    def snapshot(self, task_id: str) -> Optional[dict]:
        """Entry view for the drain-materialize path."""
        with self._cond:
            e = self._entries.get(task_id)
            if e is None:
                return None
            return {
                "batches": list(e["batches"]),
                "nparts": e["nparts"],
                "consumed": set(e["consumed"]),
                "bytes": e["bytes"],
            }

    def task_ids(self) -> List[str]:
        with self._cond:
            return list(self._entries)

    def wait(self, timeout: float) -> None:
        with self._cond:
            self._cond.wait(timeout)

    def stats(self) -> dict:
        with self._cond:
            return {
                "entries": len(self._entries),
                "bytes": sum(e["bytes"] for e in self._entries.values()),
                "hits": int(REGISTRY.counter("exchange.ici_edges").total),
                "misses": int(
                    REGISTRY.counter("exchange.ici_fallbacks").total
                ),
                "bytes_elided": int(
                    REGISTRY.counter("exchange.ici_bytes_elided").total
                ),
            }


#: the ONE in-slice exchange segment of this process (= this slice)
SEGMENT = IciSegment()


# ----------------------------------------------------- producer side


def _wire_row_bytes(page) -> int:
    """Approximate serialized bytes per row (raw typed buffers +
    packed validity) — what the HTTP wire would have moved; feeds
    ``exchange.ici_bytes_elided``."""
    total = 0
    for blk in page.blocks:
        width = blk.data.dtype.itemsize
        if blk.data.ndim == 2:
            width *= blk.data.shape[1]
        total += width
        if blk.valid is not None:
            total += 1
    return total


def _page_eligible(page) -> bool:
    """ICI-transportable page shape: fixed-width scalar blocks only
    (array/map/row blocks keep the serialized wire, which already
    handles offsets rebase and child blocks)."""
    for blk in page.blocks:
        if blk.offsets is not None or blk.children:
            return False
    return True


def _serialize_partition_slices(payload, schema, nrows, buckets):
    """Host-side partition slicing + serialization shared by the HTTP
    emit lane, the ICI spool tee, and drain materialization: yields
    ``(partition, frame, n)`` per non-empty partition, in partition
    order (np.unique), rows in producer order — the wire contract both
    transports and the spool agree on."""
    from presto_tpu.exec import streaming as S
    from presto_tpu.server import pages_wire

    for b in np.unique(buckets):
        mask = buckets == b
        sliced = S._slice_payload(payload, schema, mask)
        n = int(mask.sum())
        cols = pages_wire.payload_to_wire_columns(sliced, schema, n)
        yield int(b), pages_wire.serialize_page(cols, n), n


def emit_partitioned(task, out, *, slice_id: str, pool, fold=None) -> None:
    """The ONE partitioned-output emit (reference:
    PartitionedOutputOperator): routes this batch onto the transport
    the scheduler chose for the stage.

    ICI lane (``spec.ici_slice`` == this worker's slice): the output
    page stays device-resident — a compiled program assigns per-row
    destinations and the (page, dest) pair enters the in-slice
    segment; consumers gather their rows device-to-device. The spool
    tee still serializes under ``retry_policy=TASK`` (durability needs
    bytes on disk; the data plane between live peers stays on device).

    HTTP lane (everything else): serialize, slice per partition, offer
    to the per-partition output buffers — bit-exact legacy behavior.
    """
    import jax

    from presto_tpu.exec import streaming as S
    from presto_tpu.exec.staging import page_nbytes

    spec = task.spec
    ici_wanted = bool(spec.ici_slice)
    if ici_wanted and _ici_emit_ok(spec, out, slice_id):
        from presto_tpu.parallel import exchange as X

        import jax.numpy as jnp

        n = int(out.num_valid)
        if n == 0:
            return
        keys = tuple(spec.partition_keys)
        crc = {
            c: X.wire_crc_table(out.block(c).dictionary)
            for c in keys
            if out.block(c).dictionary is not None
        }
        stripped = X.strip_dictionaries(out)
        dest = X.bucket_dest(
            stripped, crc, jnp.asarray(spec.n_partitions), keys
        )
        _count_dispatch(1, fold)
        nbytes = page_nbytes(out) + int(dest.nbytes)
        if pool is not None:
            # same accounting as HTTP shuffle buffers: the pages are
            # stage-lifetime, reserved under the task's buffer key and
            # freed at DELETE (or at drain materialization)
            pool.reserve(task.buf_key, nbytes)

        def consumed(part: int) -> None:
            with task.cond:
                if part < len(task.complete_served):
                    task.complete_served[part] = True

        SEGMENT.publish(
            slice_id,
            spec.task_id,
            spec.n_partitions,
            out,
            dest,
            nbytes,
            on_consumed=consumed,
        )
        with task.cond:
            aborted = task.state == "ABORTED"
        if aborted:
            # a DELETE raced this batch (offer_page's abort
            # discipline): its discard ran before our publish, so the
            # re-published entry and its reservation would outlive the
            # task — undo both; any DELETE after this check discards
            # the entry itself
            freed = SEGMENT.discard(spec.task_id)
            if pool is not None and freed:
                pool.release(task.buf_key, freed)
            raise RuntimeError("task aborted")
        wire_bytes = n * _wire_row_bytes(out)
        REGISTRY.counter("exchange.ici_bytes_elided").update(
            wire_bytes
        )
        with task.cond:
            task.stats.output_rows += n
            # wire-equivalent bytes, comparable to the HTTP lane's
            # serialized counting (the device-capacity bytes are pool
            # accounting, not output volume)
            task.stats.output_bytes += wire_bytes
        if task._spool is not None:
            # durable tee: serialized frames on the shared spool dir,
            # sliced by the SAME device-computed destinations (the
            # device and host hashes are pinned equal, but recovery
            # must match what live consumers gathered, not re-derive).
            # With a drain attached the SPL1 serialization runs on its
            # background thread — durability stops charging the device
            # loop; the pre-commit flush keeps commit-marker-last.
            spool = task._spool
            tid = spec.task_id

            def tee(page=out, dvec=dest):
                payload, schema, nr = S._page_to_payload(page)
                bk = _fetch_dest(dvec, nr)
                for part, frame, _ in _serialize_partition_slices(
                    payload, schema, nr, bk
                ):
                    spool.append(tid, part, frame)

            drain = getattr(task, "_spool_drain", None)
            if drain is not None:
                drain.submit(tid, tee)
            else:
                tee()
        return

    if ici_wanted:
        # scheduler planned ICI but this attempt cannot honor it (a
        # retry landed cross-slice, or the shape is ineligible): the
        # HTTP lane is always correct
        REGISTRY.counter("exchange.ici_fallbacks").update()

    payload, schema, nrows = S._page_to_payload(out)
    if nrows == 0:
        return
    buckets = S._bucket_of(
        payload, list(spec.partition_keys), nrows, spec.n_partitions
    )
    for part, frame, n in _serialize_partition_slices(
        payload, schema, nrows, buckets
    ):
        task.offer_page(frame, part=part)
        REGISTRY.counter("exchange.http_shuffle_bytes").update(
            len(frame)
        )
        with task.cond:
            task.stats.output_rows += n


def _ici_emit_ok(spec, out, slice_id: str) -> bool:
    from presto_tpu.parallel import exchange as X

    return (
        slice_id != ""
        and spec.ici_slice == slice_id
        and 1 < spec.n_partitions <= X.MAX_ICI_PARTS
        and _page_eligible(out)
        and all(k in out.names for k in spec.partition_keys)
    )


def emit_gather(task, out, *, slice_id: str, pool, fold=None) -> bool:
    """Single-partition (gather) output onto the ICI lane: when the
    root stage is co-located with the coordinator, its final gather is
    one more ICI edge — the output page stays device-resident under an
    all-zero destination vector and the coordinator takes partition 0
    straight from the segment, no serialization and no HTTP.

    Returns True when the batch entered the segment (or was empty —
    the seal carries 'complete, zero rows'), False when the ICI lane
    cannot carry this page: the caller keeps the serialized buffer
    path, which is always correct.
    """
    import jax.numpy as jnp

    from presto_tpu.exec.staging import page_nbytes

    spec = task.spec
    if (
        slice_id == ""
        or spec.ici_slice != slice_id
        or spec.n_partitions != 1
        or not _page_eligible(out)
    ):
        if spec.ici_slice and spec.n_partitions == 1:
            REGISTRY.counter("exchange.ici_fallbacks").update()
        return False
    n = int(out.num_valid)
    if n == 0:
        return True
    dest = jnp.zeros((out.capacity,), jnp.int32)
    nbytes = page_nbytes(out) + int(dest.nbytes)
    if pool is not None:
        pool.reserve(task.buf_key, nbytes)

    def consumed(part: int) -> None:
        with task.cond:
            if part < len(task.complete_served):
                task.complete_served[part] = True

    SEGMENT.publish(
        slice_id,
        spec.task_id,
        1,
        out,
        dest,
        nbytes,
        on_consumed=consumed,
    )
    with task.cond:
        aborted = task.state == "ABORTED"
    if aborted:
        # same DELETE race discipline as emit_partitioned
        freed = SEGMENT.discard(spec.task_id)
        if pool is not None and freed:
            pool.release(task.buf_key, freed)
        raise RuntimeError("task aborted")
    wire_bytes = n * _wire_row_bytes(out)
    REGISTRY.counter("exchange.ici_bytes_elided").update(wire_bytes)
    with task.cond:
        task.stats.output_rows += n
        task.stats.output_bytes += wire_bytes
    return True


def seal_task(slice_id: str, task_id: str, nparts: int) -> None:
    """Producer FINISHED cleanly: seal before the terminal state is
    visible (same ordering as the spool commit — FINISHED must imply
    the in-slice copy is complete)."""
    SEGMENT.seal(slice_id, task_id, nparts)


def discard_task(task_id: str) -> int:
    """Task failed/aborted/DELETEd: drop its segment entry (and any
    collective-stage slabs built over it — a retried producer's new
    attempt republishes different batches); returns bytes to release
    from the task's pool reservation."""
    COLLECTIVE.discard_task(task_id)
    return SEGMENT.discard(task_id)


# Degrading a task's ICI edges to the HTTP wire happens in two
# halves so the commit is atomic: ``serialize_ici_frames`` is a pure
# read (no buffer side effects — an exception leaves nothing torn and
# the degrade can simply retry), ``buffer_frames`` reserves once and
# appends everything under ONE lock hold (pullers observe the buffers
# either empty or complete, never a torn prefix that could flip
# X-Complete early). Two callers drive the pair through
# ``WorkerServer._materialize_ici``: a DRAINING producer (its ICI
# edges must fall back so the zero-failure-drain contract holds) and
# the results handler's lazy path — an HTTP pull of a FINISHED ICI
# task (a merge retry that landed cross-slice) must see the real
# pages, never an empty-but-complete buffer. EVERY partition
# materializes, including ones an ICI consumer already took:
# partitioned buffers serve retried merge attempts from token 0 by
# contract, exactly like the HTTP lane's DELETE-lifetime buffers.


def serialize_ici_frames(task):
    """First half: the task's in-segment batches as
    ``[(partition, frame), ...]`` serialized wire frames, or None when
    no segment entry exists. Pure read — no buffers touched, no
    reservations made."""
    import jax

    from presto_tpu.exec import streaming as S

    snap = SEGMENT.snapshot(task.spec.task_id)
    if snap is None:
        return None
    frames = []
    for page, dest in snap["batches"]:
        payload, schema, nr = S._page_to_payload(page)
        bk = _fetch_dest(dest, nr)
        for part, frame, _ in _serialize_partition_slices(
            payload, schema, nr, bk
        ):
            frames.append((part, frame))
    return frames


def buffer_frames(task, frames, pool) -> int:
    """Second half: commit serialized frames to the task's
    per-partition HTTP buffers — one reservation for the whole set
    (direct appends, NOT offer_page: the spool tee already ran at
    produce time; teeing again would double-serve recovery), one
    locked append of everything, then the segment entry drops and its
    device-byte reservation releases."""
    total = sum(len(f) for _, f in frames)
    if pool is not None and total:
        pool.reserve(task.buf_key, total)
    with task.cond:
        for part, frame in frames:
            task.parts[part].append(frame)
    for _, frame in frames:
        REGISTRY.counter("exchange.http_shuffle_bytes").update(
            len(frame)
        )
    freed = SEGMENT.discard(task.spec.task_id)
    if freed and pool is not None:
        pool.release(task.buf_key, freed)
    if frames:
        REGISTRY.counter("exchange.ici_materialized").update()
    return len(frames)


# ----------------------------------------------------- consumer side


def ici_fetch(
    slice_id: str,
    spec,
    src_task: str,
    deadline: float,
    probe,
):
    """Consumer half of the ICI transport: wait for the producer's
    segment entry to seal, then take this merge task's partition.

    Returns the ``[(page, dest), ...]`` batch list, or None — the
    caller falls back to the HTTP pull (then the spool), exactly the
    recovery ladder a dead HTTP peer takes today. ``probe()`` answers
    whether the producer attempt is still alive (True = keep waiting,
    False = terminal/unreachable); it is only consulted between waits,
    so the control-plane HTTP stays off the hot path."""
    if not spec.ici_slice or spec.ici_slice != slice_id:
        return None
    last_probe = 0.0
    while True:
        st = SEGMENT.peek(slice_id, src_task)
        if st == "sealed":
            got = SEGMENT.take(slice_id, src_task, spec.partition)
            if got is not None:
                REGISTRY.counter("exchange.ici_edges").update()
                return got
            break
        if st == "foreign":
            break
        now = time.monotonic()
        if now > deadline:
            break
        if now - last_probe > 0.5:
            last_probe = now
            alive = probe()
            if alive is False:
                # terminal: the producer seals BEFORE publishing
                # FINISHED, so sealed-or-never is decidable now
                if SEGMENT.peek(slice_id, src_task) == "sealed":
                    continue
                break
        SEGMENT.wait(0.05)
    REGISTRY.counter("exchange.ici_fallbacks").update()
    return None


def ici_batches_to_payloads(batches, part: int, schema):
    """Degrade an ICI batch list to host wire payloads
    ``[(payload, schema, nrows), ...]`` — the shape
    ``pages_wire.merge_payloads`` consumes. Used when a merge group
    mixes transports (some sources fell back to HTTP) or exceeds the
    device budget (the grouped host merge takes over): still zero
    serialization and zero HTTP, one device->host fetch."""
    import jax

    from presto_tpu.exec import streaming as S

    out = []
    for page, dest in batches:
        payload, pschema, nr = S._page_to_payload(page)
        bk = _fetch_dest(dest, nr)
        mask = bk == part
        n = int(mask.sum())
        if n == 0:
            continue
        out.append((S._slice_payload(payload, pschema, mask), pschema, n))
    return out


def device_merge(
    batches_by_source, part: int, schema, max_rows=None, fold=None
):
    """Build the merge task's input page ON DEVICE from ICI batches:
    per-source partition rows gather-scattered into one zero-padded
    buffer (``parallel.exchange.ici_append``), dictionary ids remapped
    into the sorted union dictionary — the same union, row order, and
    capacity bucket the HTTP path's ``merge_payloads`` + ``stage_page``
    produce, so the downstream fragment compiles and computes
    identically.

    Returns ``(page, total_rows)``, or None when the partition exceeds
    ``max_rows`` — the caller degrades to the grouped host merge
    (``ici_batches_to_payloads`` + ``grouped_final_merge``), the same
    memory-funnel discipline the HTTP gather applies.
    """
    import jax
    import jax.numpy as jnp

    from presto_tpu.exec.staging import bucket_capacity
    from presto_tpu.page import Block, Dictionary, Page
    from presto_tpu.parallel import exchange as X

    flat: List[tuple] = [
        b for src in batches_by_source for b in src
    ]
    names = tuple(schema.keys())
    # one small device->host fetch sizes the buffer (counts only —
    # the data plane stays on device)
    count_vecs = jax.device_get(
        [X.ici_partition_counts(pg, d) for pg, d in flat]
    )
    d2h = sum(int(np.asarray(c).nbytes) for c in count_vecs)
    DEVICE.count_d2h(d2h)
    _count_dispatch(len(flat), fold)
    if fold is not None:
        fold(device_d2h_bytes=d2h)
    counts = [int(np.asarray(c)[part]) for c in count_vecs]
    total = int(sum(counts))
    if max_rows is not None and total > max_rows:
        return None
    cap = bucket_capacity(total)

    # per-column union dictionary + per-source remap tables, exactly
    # merge_payloads' sorted-union searchsorted
    union: Dict[str, Optional[list]] = {}
    has_valid: Dict[str, bool] = {}
    for name in names:
        dicts = []
        anyv = False
        for pg, _ in flat:
            blk = pg.block(name)
            if blk.dictionary is not None:
                dicts.append(tuple(blk.dictionary.values))
            if blk.valid is not None:
                anyv = True
        union[name] = (
            sorted(set().union(*dicts)) if dicts else None
        )
        has_valid[name] = anyv

    out = {}
    for name in names:
        t = schema[name]
        tail = (2,) if getattr(t, "is_long_decimal", False) else ()
        for pg, _ in flat:
            d = pg.block(name).data
            tail = (d.shape[1],) if d.ndim == 2 else ()
            break
        out[name] = {
            "data": jnp.zeros((cap,) + tail, t.np_dtype),
            "valid": (
                jnp.zeros((cap,), jnp.bool_)
                if has_valid[name]
                else None
            ),
        }

    offset = 0
    for (pg, dest), cnt in zip(flat, counts):
        remaps = {}
        for name in names:
            u = union[name]
            blk = pg.block(name)
            if u is not None and blk.dictionary is not None:
                uarr = np.asarray(u, object)
                vals = np.asarray(blk.dictionary.values, object)
                remaps[name] = jnp.asarray(
                    np.searchsorted(uarr, vals).astype(np.int64)
                )
            else:
                remaps[name] = None
        out = X.ici_append(
            out,
            X.strip_dictionaries(pg),
            dest,
            jnp.asarray(part, jnp.int32),
            jnp.asarray(offset, jnp.int32),
            remaps,
        )
        _count_dispatch(1, fold)
        offset += cnt

    blocks = []
    for name in names:
        u = union[name]
        blocks.append(
            Block(
                data=out[name]["data"],
                valid=out[name]["valid"],
                dtype=schema[name],
                dictionary=(
                    Dictionary(np.asarray(u, object))
                    if u is not None
                    else None
                ),
            )
        )
    page = Page(
        blocks=tuple(blocks),
        num_valid=jnp.asarray(total, jnp.int32),
        names=names,
    )
    return page, total


# ------------------------------------------------- collective stages


class _CollectiveCache:
    """One single-program exchange per (slice, producer set): the
    first merge task of a stage builds the collective program's output
    slabs (ONE ``shard_map``/``all_to_all`` dispatch for every batch of
    every producer — ``parallel.exchange.collective_gather``); sibling
    merge tasks take their partitions from the same slabs instead of
    re-gathering per source. Entries wrap device arrays and die when
    every partition is served or when any producer task is discarded
    (a retried attempt republishes different batches). Build failures
    and size refusals are cached too, so siblings fail open to the
    per-source path without re-tracing."""

    def __init__(self):
        self._cond = threading.Condition()
        self._entries: Dict[tuple, dict] = {}

    def lookup(self, key, builder):
        """The built stage entry for ``key`` (or None when the build
        failed/refused). The first caller builds OUTSIDE the lock;
        concurrent siblings wait on the condition instead of building
        twice."""
        with self._cond:
            while True:
                e = self._entries.get(key)
                if e is None:
                    e = {
                        "state": "building",
                        "entry": None,
                        "served": set(),
                    }
                    self._entries[key] = e
                    break
                if e["state"] == "building":
                    self._cond.wait(1.0)
                    continue
                return e["entry"]
        built = None
        try:
            built = builder()
        except Exception as exc:
            log.info(
                "collective stage build failed (%s); "
                "falling back to the per-source gather",
                exc,
            )
            REGISTRY.counter("exchange.collective_fallbacks").update()
        with self._cond:
            e["state"] = "ready" if built is not None else "failed"
            e["entry"] = built
            self._cond.notify_all()
        return built

    def served(self, key, part: int, nparts: int) -> None:
        with self._cond:
            e = self._entries.get(key)
            if e is None or e["state"] == "building":
                return
            e["served"].add(int(part))
            if len(e["served"]) >= int(nparts):
                self._entries.pop(key, None)

    def discard_task(self, task_id: str) -> None:
        with self._cond:
            for k in [k for k in self._entries if task_id in k[1]]:
                self._entries.pop(k, None)

    def stats(self) -> dict:
        with self._cond:
            return {"entries": len(self._entries)}


#: the ONE collective-stage cache of this process (= this slice)
COLLECTIVE = _CollectiveCache()


def _build_collective(flat, batch_src, schema, nparts, max_rows, fold):
    """Dispatch the single-program exchange over ``flat`` (all batches
    of all ICI sources, source-major order): one counts program sizes
    the slabs, one collective program routes every row — versus one
    counts + one append program PER BATCH on the per-source path.
    Returns the stage entry dict, or None when any partition would
    exceed ``max_rows`` (the caller degrades to the grouped host
    merge, the same memory funnel the per-source path applies)."""
    import jax
    import jax.numpy as jnp

    from presto_tpu.exec.staging import bucket_capacity
    from presto_tpu.parallel import exchange as X

    names = tuple(schema.keys())
    pages = tuple(X.strip_dictionaries(pg) for pg, _ in flat)
    dests = tuple(d for _, d in flat)

    # per-column union dictionary + per-batch remap tables — the
    # sorted-union searchsorted discipline merge_payloads pins; the
    # remap itself applies IN-PROGRAM
    union: Dict[str, Optional[list]] = {}
    has_valid: Dict[str, bool] = {}
    for name in names:
        dicts = []
        anyv = False
        for pg, _ in flat:
            blk = pg.block(name)
            if blk.dictionary is not None:
                dicts.append(tuple(blk.dictionary.values))
            if blk.valid is not None:
                anyv = True
        union[name] = sorted(set().union(*dicts)) if dicts else None
        has_valid[name] = anyv
    remaps = []
    for pg, _ in flat:
        rm = {}
        for name in names:
            u = union[name]
            blk = pg.block(name)
            if u is not None and blk.dictionary is not None:
                uarr = np.asarray(u, object)
                vals = np.asarray(blk.dictionary.values, object)
                rm[name] = jnp.asarray(
                    np.searchsorted(uarr, vals).astype(np.int64)
                )
        remaps.append(rm)

    counts = np.asarray(
        jax.device_get(X.collective_counts(pages, dests, nparts))
    )
    _count_dispatch(1, fold)
    DEVICE.count_d2h(int(counts.nbytes))
    if fold is not None:
        fold(device_d2h_bytes=int(counts.nbytes))
    totals = counts.sum(axis=0)
    peak = int(totals.max(initial=0))
    if max_rows is not None and peak > max_rows:
        return None
    out_cap = bucket_capacity(peak)
    dtypes = {name: schema[name].np_dtype for name in names}
    out = X.collective_gather(
        pages, dests, tuple(remaps), dtypes, nparts, out_cap
    )
    _count_dispatch(1, fold)
    REGISTRY.counter("exchange.collective_stages").update()
    return {
        "out": out,
        "counts": counts,
        "totals": totals,
        "union": union,
        "names": names,
        "batch_src": tuple(batch_src),
    }


def _collective_page(entry, part: int, schema, fold):
    """One partition of a built stage entry as a Page — a single
    static-shape slice program per partition, same union dictionary,
    row order (flat batch order) and capacity bucket as
    :func:`device_merge`."""
    import jax.numpy as jnp

    from presto_tpu.exec.staging import bucket_capacity
    from presto_tpu.page import Block, Dictionary, Page

    from presto_tpu.parallel import exchange as X

    total = int(entry["totals"][part])
    pcap = bucket_capacity(total)
    taken = X.collective_take(
        entry["out"],
        entry["names"],
        jnp.asarray(part, jnp.int32),
        pcap,
    )
    _count_dispatch(1, fold)
    blocks = []
    for name in entry["names"]:
        u = entry["union"][name]
        blocks.append(
            Block(
                data=taken[name]["data"],
                valid=taken[name]["valid"],
                dtype=schema[name],
                dictionary=(
                    Dictionary(np.asarray(u, object))
                    if u is not None
                    else None
                ),
            )
        )
    return (
        Page(
            blocks=tuple(blocks),
            num_valid=jnp.asarray(total, jnp.int32),
            names=entry["names"],
        ),
        total,
    )


def _collective_flat(batches_by_source):
    """Source-major flattening shared by the collective entry points —
    the flat batch order IS the output row order, so it must match the
    merge task's source order exactly."""
    flat: List[tuple] = []
    batch_src: List[int] = []
    for i, src in enumerate(batches_by_source):
        for b in src:
            flat.append(b)
            batch_src.append(i)
    return flat, batch_src


def collective_merge(
    slice_id: str,
    srcs,
    batches_by_source,
    part: int,
    schema,
    nparts: int,
    max_rows=None,
    fold=None,
):
    """Single-program variant of :func:`device_merge`: ONE collective
    dispatch routes every source's batches for ALL partitions at once;
    this merge task takes partition ``part`` from the shared slabs.
    Bit-identical output (union dictionaries, flat-batch row order,
    zero-padded capacity bucket). Returns ``(page, total)`` or None —
    the caller falls back to :func:`device_merge` (then the grouped
    host merge), the PR 14 per-source path."""
    flat, batch_src = _collective_flat(batches_by_source)
    if not flat:
        return None
    key = (slice_id, tuple(srcs), int(nparts))
    entry = COLLECTIVE.lookup(
        key,
        lambda: _build_collective(
            flat, batch_src, schema, nparts, max_rows, fold
        ),
    )
    got = None
    if entry is not None:
        try:
            got = _collective_page(entry, part, schema, fold)
        except Exception as exc:
            log.info(
                "collective take failed (%s); per-source fallback", exc
            )
            REGISTRY.counter("exchange.collective_fallbacks").update()
    COLLECTIVE.served(key, part, nparts)
    return got


def collective_payloads(
    slice_id: str,
    srcs,
    batches_by_source,
    part: int,
    schema,
    nparts: int,
    fold=None,
):
    """Mixed-transport splice: the ICI sources' share of ``part`` out
    of the SAME collective program, degraded to host wire payloads —
    one (possibly empty) ``[(payload, schema, nrows), ...]`` list per
    source, index-aligned with ``batches_by_source`` — ready to
    interleave with the HTTP sources' payloads under
    ``merge_payloads``'s union-merge discipline (bit-equal to the wire
    path). Returns None when the collective program is unavailable;
    the caller degrades to :func:`ici_batches_to_payloads` per
    source."""
    from presto_tpu.exec import streaming as S

    flat, batch_src = _collective_flat(batches_by_source)
    if not flat:
        return None
    key = (slice_id, tuple(srcs), int(nparts))
    entry = COLLECTIVE.lookup(
        key,
        lambda: _build_collective(
            flat, batch_src, schema, nparts, None, fold
        ),
    )
    got = None
    if entry is not None:
        try:
            page, total = _collective_page(entry, part, schema, fold)
            payload, pschema, nr = S._page_to_payload(page)
            out = []
            start = 0
            nsrc = len(batches_by_source)
            for i in range(nsrc):
                n_i = int(
                    sum(
                        entry["counts"][b][part]
                        for b in range(len(flat))
                        if entry["batch_src"][b] == i
                    )
                )
                if n_i:
                    mask = np.zeros((nr,), bool)
                    mask[start : start + n_i] = True
                    out.append(
                        [
                            (
                                S._slice_payload(
                                    payload, pschema, mask
                                ),
                                pschema,
                                n_i,
                            )
                        ]
                    )
                else:
                    out.append([])
                start += n_i
            got = out
        except Exception as exc:
            log.info(
                "collective splice failed (%s); per-source fallback",
                exc,
            )
            REGISTRY.counter("exchange.collective_fallbacks").update()
    COLLECTIVE.served(key, part, nparts)
    return got


def ici_gather(slice_id: str, spec, deadline: float, probe, fold=None):
    """Coordinator half of the ICI gather edge: when the root stage's
    single-partition output is co-located, take it straight from the
    segment — the final gather stops paying serialization + HTTP.

    Returns host payloads ``[(payload, schema, nrows), ...]`` (the
    shape the result assembly consumes), or None — the caller falls
    back to the HTTP pull, which remains fully correct (the worker
    lazily materializes the segment on first HTTP read)."""
    if (
        not slice_id
        or spec.ici_slice != slice_id
        or spec.n_partitions != 1
    ):
        return None
    src = spec.task_id
    last_probe = 0.0
    while True:
        st = SEGMENT.peek(slice_id, src)
        if st == "sealed":
            got = SEGMENT.take(slice_id, src, 0)
            if got is not None:
                REGISTRY.counter("exchange.ici_edges").update()
                return ici_batches_to_payloads(got, 0, None)
            break
        if st == "foreign":
            break
        now = time.monotonic()
        if now > deadline:
            break
        if now - last_probe > 0.5:
            last_probe = now
            alive = probe()
            if alive is False:
                if SEGMENT.peek(slice_id, src) == "sealed":
                    continue
                break
        SEGMENT.wait(0.05)
    REGISTRY.counter("exchange.ici_fallbacks").update()
    return None
