"""Unified RPC plane for every coordinator<->worker<->client HTTP call.

Reference parity: presto routes all intra-cluster traffic through one
airlift HttpClient with per-client config-driven timeouts, and treats
node failure detection / recoverable execution as coordinator duties
(SURVEY.md §2.5, §5.3). Here the single helper replaces the ad-hoc
``urllib.request.urlopen`` call sites (``tools/check_rpc_calls.py``
enforces that) and adds what raw urlopen lacks:

- per-call, config-driven timeouts on a **monotonic** clock,
- bounded retries with exponential backoff + **full jitter** for
  connection-level failures on idempotent calls (POSTs are never
  retried here — task creation is made idempotent one level up, where
  the coordinator mints a fresh task id per attempt),
- fault-plane hooks (:mod:`presto_tpu.utils.faults`) before every
  attempt, so chaos tests inject at the one choke point,
- ``rpc.*`` metrics (requests / failures / retries / time).

The module also owns :class:`CircuitBreaker` — per-peer health memory
(CLOSED -> OPEN after N consecutive failures -> one HALF_OPEN probe ->
CLOSED) that the coordinator keys by worker node id and folds into
scheduling next to the discovery TTL.
"""

from __future__ import annotations

import dataclasses
import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional

from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY

#: connection-level failures eligible for retry. ``TimeoutError`` and
#: ``socket.timeout`` are OSErrors; ``HTTPError`` is excluded by
#: :func:`is_retryable` — the server answered, so re-sending cannot
#: change the outcome.
RETRYABLE_EXCS = (urllib.error.URLError, ConnectionError, OSError)

#: backoff jitter source when no seeded fault plane is active
_RNG = random.Random()


def backoff_rng() -> random.Random:
    """Full-jitter RNG: the fault plane's dedicated backoff stream
    when chaos is configured (deterministic schedules for seeded,
    single-threaded draws — concurrent threads still interleave),
    else the module default."""
    plane = faults.active()
    return plane.backoff_rng if plane is not None else _RNG


def is_retryable(exc: BaseException) -> bool:
    """Connection-level failure (dead socket, refused, timed out) —
    NOT an HTTP error response, which is an answer, not a failure."""
    return isinstance(exc, RETRYABLE_EXCS) and not isinstance(
        exc, urllib.error.HTTPError
    )


def is_task_recoverable(exc: BaseException) -> bool:
    """A failure that means the PEER cannot own the task rather than
    the task itself failing: any connection-level failure, a 404 on a
    task endpoint (the worker crashed + restarted under the same URI
    and lost the task), or a 503 (the worker is DRAINING and rejects
    new tasks). Recoverable by rescheduling on another worker; every
    other HTTP error is an execution failure that would fail anywhere."""
    if is_retryable(exc):
        return True
    return isinstance(exc, urllib.error.HTTPError) and exc.code in (
        404,
        503,
    )


@dataclasses.dataclass(frozen=True)
class RpcPolicy:
    """Per-call knobs, config-driven (reference: airlift HttpClient
    config keys)."""

    timeout_s: float = 30.0
    retries: int = 2
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    #: token-acked page-pull requests kept in flight per pull loop
    #: (``rpc.pull-depth``): 1 = strict request->ack->request; 2+
    #: overlaps the next page's network round trip with this page's
    #: deserialization (see :func:`pull_pages`)
    pull_depth: int = 2

    @staticmethod
    def from_config(config) -> "RpcPolicy":
        """Policy from NodeConfig ``rpc.*`` keys (defaults preserve the
        previously hardcoded 30 s request timeout)."""
        if config is None:
            return RpcPolicy()
        return RpcPolicy(
            timeout_s=float(config.get("rpc.request-timeout-s", 30.0)),
            retries=int(config.get("rpc.retries", 2)),
            backoff_base_s=float(config.get("rpc.backoff-base-s", 0.05)),
            backoff_max_s=float(config.get("rpc.backoff-max-s", 2.0)),
            pull_depth=int(config.get("rpc.pull-depth", 2)),
        )


DEFAULT_POLICY = RpcPolicy()

#: shared executor for pipelined page pulls: one process-wide pool
#: instead of a fresh ThreadPoolExecutor per pull (no thread churn per
#: task stream). Speculative fetches are plain bounded-timeout GETs —
#: no inter-future dependencies, so a shared pool cannot deadlock;
#: abandoned fetches finish within the rpc timeout and their results
#: are dropped.
_PULL_POOL = None
_PULL_POOL_LOCK = threading.Lock()
_PULL_POOL_WORKERS = 32


def _pull_executor():
    global _PULL_POOL
    with _PULL_POOL_LOCK:
        if _PULL_POOL is None:
            from concurrent.futures import ThreadPoolExecutor

            _PULL_POOL = ThreadPoolExecutor(
                max_workers=_PULL_POOL_WORKERS,
                thread_name_prefix="page-pull",
            )
        return _PULL_POOL


def compute_backoff(
    attempt: int,
    policy: RpcPolicy = DEFAULT_POLICY,
    rng: Optional[random.Random] = None,
) -> float:
    """Exponential backoff with full jitter: uniform(0, min(cap,
    base * 2^attempt)). Full jitter (vs equal or none) de-correlates
    retry storms from many callers hitting one recovering peer."""
    cap = min(
        policy.backoff_max_s, policy.backoff_base_s * (2.0 ** attempt)
    )
    return (rng or backoff_rng()).uniform(0.0, cap)


@dataclasses.dataclass
class RpcResponse:
    """One successful HTTP exchange (2xx, including bodyless 204)."""

    status: int
    headers: object  # email.message.Message: case-insensitive .get
    body: bytes

    def json(self) -> dict:
        return json.loads(self.body) if self.body else {}


def call(
    method: str,
    url: str,
    body: Optional[bytes] = None,
    *,
    policy: RpcPolicy = DEFAULT_POLICY,
    timeout_s: Optional[float] = None,
    headers=None,
    traceparent: str = "",
    idempotent: Optional[bool] = None,
) -> RpcResponse:
    """One RPC with bounded retries.

    Retries apply only to idempotent calls (default: every method but
    POST) and only for connection-level failures — an HTTP error
    status or an application exception propagates immediately. Sleeps
    between attempts follow :func:`compute_backoff`.
    """
    if idempotent is None:
        idempotent = method != "POST"
    hdrs = dict(headers or ())
    if traceparent:
        hdrs["traceparent"] = traceparent
    timeout = policy.timeout_s if timeout_s is None else timeout_s
    attempts = (policy.retries if idempotent else 0) + 1
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        if attempt:
            REGISTRY.counter("rpc.retries").update()
            time.sleep(compute_backoff(attempt - 1, policy))
        try:
            faults.maybe_inject_rpc(method, url)
            req = urllib.request.Request(
                url, data=body, method=method, headers=hdrs
            )
            with REGISTRY.timer("rpc.time").time():
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    out = RpcResponse(r.status, r.headers, r.read())
            REGISTRY.counter("rpc.requests").update()
            return out
        except Exception as e:
            REGISTRY.counter("rpc.failures").update()
            last = e
            if not (idempotent and is_retryable(e)):
                raise
    assert last is not None
    raise last


def call_json(method: str, url: str, obj=None, **kw) -> dict:
    """JSON-in/JSON-out convenience over :func:`call`."""
    hdrs = dict(kw.pop("headers", None) or ())
    hdrs.setdefault("Content-Type", "application/json")
    body = json.dumps(obj).encode() if obj is not None else None
    return call(method, url, body, headers=hdrs, **kw).json()


def pull_pages(
    uri: str,
    task_id: str,
    buffer: int,
    *,
    policy: RpcPolicy = DEFAULT_POLICY,
    deadline_s: float = 3600.0,
    traceparent: str = "",
    stall=None,
    timeout_msg: str = "",
    depth: Optional[int] = None,
) -> list:
    """The token-acked exchange pull loop (one implementation for the
    coordinator's gather and the worker's shuffle read): GET
    ``/v1/task/{id}/results/{buffer}/{token}`` until ``X-Complete``,
    advancing the token per ``X-Next-Token``. Returns the deserialized
    pages.

    Pipelining (``depth``, default ``policy.pull_depth``): up to
    ``depth`` token requests stay in flight concurrently, so page
    N+1's network round trip overlaps page N's decompress/deserialize
    instead of strictly alternating. Every request carries an
    ``X-Ack`` header with the CONSUMED floor — the producer frees only
    pages the puller has actually received, so a speculative in-flight
    request can never free an unconsumed page (with depth 1 the floor
    equals the requested token, the historical ack-via-URL behavior).

    ``stall()`` runs when no page is ready yet (default: short sleep);
    callers use it to poll task status and surface failures. The
    deadline is monotonic."""
    from presto_tpu.server import pages_wire

    depth = max(1, policy.pull_depth if depth is None else int(depth))
    out: list = []
    deadline = time.monotonic() + deadline_s

    def fetch(t: int, ack: int) -> RpcResponse:
        return call(
            "GET",
            f"{uri}/v1/task/{task_id}/results/{buffer}/{t}",
            policy=policy,
            traceparent=traceparent,
            headers={"X-Ack": str(ack)},
        )

    def timed_out() -> bool:
        return time.monotonic() > deadline

    def fail_timeout():
        raise TimeoutError(
            timeout_msg or f"pull of {task_id}[{buffer}] timed out"
        )

    token = 0
    if depth == 1:
        while True:
            if timed_out():
                fail_timeout()
            resp = fetch(token, token)
            complete = resp.headers.get("X-Complete") == "true"
            nxt = int(resp.headers.get("X-Next-Token", token))
            if resp.status == 200:
                out.append(pages_wire.deserialize_page(resp.body))
            if complete and nxt == token + (
                1 if resp.status == 200 else 0
            ):
                return out
            if nxt == token and resp.status != 200:
                if stall is not None:
                    stall()
                else:
                    time.sleep(0.02)
            token = nxt
        # not reached

    inflight: dict = {}
    executor = _pull_executor()
    try:
        while True:
            if timed_out():
                fail_timeout()
            # keep the window full: tokens [consumed, consumed+depth)
            for t in range(token, token + depth):
                if t not in inflight:
                    inflight[t] = executor.submit(fetch, t, token)
            resp = inflight.pop(token).result()
            complete = resp.headers.get("X-Complete") == "true"
            if resp.status == 200:
                out.append(pages_wire.deserialize_page(resp.body))
                token += 1
                if complete:
                    # that was the final page
                    return out
                continue
            # 204: no page at this token (a speculative response may
            # be stale — re-request rather than trusting it)
            if complete:
                return out
            if stall is not None:
                stall()
            else:
                time.sleep(0.02)
    finally:
        for f in inflight.values():
            f.cancel()


class CircuitBreaker:
    """Per-peer health memory (consecutive-failure scoring).

    CLOSED counts consecutive connection-level failures; at
    ``threshold`` the circuit OPENs and :meth:`allow` excludes the peer
    for ``open_s`` seconds (monotonic clock — wall jumps cannot reopen
    or pin it). After that, HALF_OPEN admits ONE probe: probe success
    re-CLOSEs, probe failure re-OPENs. A granted probe that never
    resolves (its query died elsewhere) re-arms after another
    ``open_s``, so a lost probe cannot wedge the breaker.

    ``transitions`` records every state change in order — the
    OPEN -> HALF_OPEN -> CLOSED cycle asserted by the chaos suite.
    """

    def __init__(self, threshold: int = 3, open_s: float = 5.0):
        self.threshold = threshold
        self.open_s = open_s
        self.state = "CLOSED"
        self.transitions: List[str] = []
        self._fails = 0
        self._opened = 0.0
        self._probe_at = 0.0
        self._lock = threading.Lock()

    def _to(self, state: str) -> bool:
        if state == self.state:
            return False
        self.state = state
        self.transitions.append(state)
        return True

    def peek(self) -> str:
        """Current state, without consuming a probe slot."""
        with self._lock:
            return self.state

    def allow(self) -> bool:
        """May this peer be scheduled to right now? OPEN -> HALF_OPEN
        promotion and probe-slot accounting happen here."""
        with self._lock:
            if self.state == "CLOSED":
                return True
            now = time.monotonic()
            if (
                self.state == "OPEN"
                and now - self._opened >= self.open_s
            ):
                self._to("HALF_OPEN")
                self._probe_at = 0.0
            if self.state == "HALF_OPEN" and (
                self._probe_at == 0.0
                or now - self._probe_at >= self.open_s
            ):
                self._probe_at = now
                return True
            return False

    def record_success(self) -> bool:
        """True when this success CLOSEd a half-open circuit."""
        with self._lock:
            self._fails = 0
            self._probe_at = 0.0
            return self._to("CLOSED")

    def record_failure(self) -> bool:
        """True when this failure OPENed the circuit."""
        with self._lock:
            self._fails += 1
            if self.state == "HALF_OPEN" or (
                self.state == "CLOSED" and self._fails >= self.threshold
            ):
                self._opened = time.monotonic()
                self._probe_at = 0.0
                return self._to("OPEN")
            return False
