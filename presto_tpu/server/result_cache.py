"""Serving-plane result reuse: the snapshot-keyed result cache and
the MV-aware scan rewrite (reference: Presto's fragment result cache
+ materialized-view rewrite, alluded to in every dashboard-shaped
deployment story).

This is the ONE audited module of the result-reuse plane
(``result-cache-plane`` lint): cache construction, the
fingerprint×snapshot key minting, and the MV rewrite seam live here;
the coordinator and the planner seam in ``exec/local_runner.py`` are
the audited consumers.

Three composable tiers, all keyed on what the engine already knows to
be true:

(a) **Snapshot-keyed result cache** (:class:`ResultCache`): entries
    key on the canonical statement fingerprint (the PR 6
    literal-hoisted form — ``x < 24`` and ``x < 30`` share a plan but
    mint DISTINCT result keys because the hoisted literal vector is
    part of the key) × the catalog/schema the statement resolved
    against × session flags that pick the execution backend. A hit is
    zero planning and zero dispatch. Freshness is a snapshot compare:
    the entry records the ``TableHandle.snapshot`` vector pinned at
    plan time (PR 12) plus a per-table write generation bumped through
    the one audited write seam (``_invalidate_table_caches`` fan-in —
    legacy INSERTs and ingest commits both route through it), and a
    ``get`` re-pins every table to detect commits the local seam never
    saw. Entries are byte-budgeted through ``utils/memory.MemoryPool``
    under the ``result-cache`` owner with LRU eviction.

(b) **MV-aware rewrite** (:func:`mview_rewrite`): an eligible
    single-table aggregate SELECT whose shape matches a registered
    materialized view rewrites its scan onto the maintained MV without
    the reader naming it, under the same ``mview.max-staleness-s``
    read-gate discipline named reads get. With the gate off, only a
    provably-current view (base write epoch covered by the view
    state) rewrites — a reader of the BASE table never silently gets
    unbounded staleness it did not opt into.

(c) **Stale-tolerant serving**: a write marks entries STALE instead of
    dropping them; a later read within the session's
    ``result_cache_max_staleness_s`` bound serves the stale result
    (counted, surfaced in EXPLAIN ANALYZE) while ONE background
    refresh re-executes and replaces the entry. Beyond the bound the
    entry drops and the read executes normally.

Everything fails OPEN: any error in key minting, freshness probing, or
rewriting degrades to normal planning + execution, never to a failed
query. Default off (``result-cache.enabled=false`` / session
``enable_result_cache``) = bit-exact pre-PR behavior.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from presto_tpu.utils.metrics import REGISTRY

#: a single entry may not exceed this fraction of the cache budget —
#: one huge result must not evict the whole working set
_MAX_ENTRY_FRACTION = 8


# ------------------------------------------------------------- key minting


def statement_key(stmt, session) -> Optional[tuple]:
    """Mint the result-cache key of one SELECT: canonical statement
    fingerprint × hoisted-literal value vector × session flags that
    change what executes. Returns None when the statement cannot be
    canonicalized (the caller falls open to normal execution).

    The literal vector uses ``repr`` of the hoisted Literal nodes, so
    ``x < 24`` and ``x < 30`` (same canonical form, same cached plan)
    mint distinct RESULT keys, and ``1`` vs ``1.0`` never collide.
    Catalog/schema are inside the canonical key already (name
    resolution depends on them); ``tpu_offload`` rides along because
    it selects the execution backend."""
    from presto_tpu.plan import canonical

    try:
        key, _canon, lits = canonical.canonicalize_statement(
            stmt, session
        )
        flags = (bool(session.get("tpu_offload")),)
        return (key, tuple(repr(v) for v in lits), flags)
    except Exception:
        return None


def snapshot_vector(handles, catalogs) -> Optional[tuple]:
    """The freshness identity of one executed plan: a sorted tuple of
    ``(table_key, snapshot)`` over every scanned table, with the
    snapshot as pinned at plan time (PR 12). None when ANY scanned
    catalog is non-cacheable (system.runtime.* and other live
    introspection sources must never serve stale) — the caller skips
    the put."""
    vec = []
    for h in handles:
        conn = catalogs.get(h.catalog)
        if conn is None or not conn.cacheable():
            return None
        vec.append((h.table_key, h.snapshot))
    return tuple(sorted(vec))


def _snapshot_label(vector: tuple) -> str:
    """Human form of the pinned snapshot vector for EXPLAIN ANALYZE
    ('v12', 'v3,v7', or 'unversioned')."""
    snaps = [s for _tk, s in vector]
    if not snaps or all(s is None for s in snaps):
        return "unversioned"
    return ",".join("v?" if s is None else f"v{s}" for s in snaps)


# ----------------------------------------------------------------- entries


class CachedResult:
    """Duck-typed stand-in for ``exec.local_runner.QueryResult`` a
    cache hit returns: the coordinator only reads ``columns`` and
    ``rows()`` when storing client-visible results."""

    __slots__ = ("columns", "_rows")

    def __init__(self, columns: Tuple[str, ...], rows: List[list]):
        self.columns = columns
        self._rows = rows

    def rows(self) -> List[list]:
        return self._rows


@dataclasses.dataclass
class ResultEntry:
    key: tuple
    #: the ORIGINAL (pre-rewrite) statement AST — what a background
    #: refresh re-plans (the rewrite seam re-applies itself there)
    stmt: Any
    columns: Tuple[str, ...]
    rows: List[list]
    #: sorted ((catalog, schema, table), snapshot) pinned at plan time
    vector: tuple
    #: per-table write generations (cache-local counters bumped by
    #: :meth:`ResultCache.note_write`) captured at put time
    gens: tuple
    nbytes: int
    created_at: float
    snapshot_label: str
    #: 0.0 = believed fresh; else the instant the entry was first
    #: observed stale (write fan-in or snapshot mismatch) — the clock
    #: the bounded-staleness serve measures against
    stale_at: float = 0.0
    #: one background refresh at a time per entry
    refreshing: bool = False
    hits: int = 0


def _estimate_nbytes(columns, rows) -> int:
    """Cheap, stable byte estimate of a materialized result (what the
    MemoryPool reservation charges): per-row/list overhead plus the
    payload of strings and bytes."""
    n = 256 + 16 * len(columns)
    for row in rows:
        n += 64
        for v in row:
            n += 16
            if isinstance(v, (str, bytes)):
                n += len(v)
    return n


# ------------------------------------------------------------- the cache


class ResultCache:
    """Coordinator-side snapshot-keyed result cache (tier a + c).

    Thread-safe; every public method fails open (returns a miss /
    skips the put) rather than raising. The MemoryPool reservation
    under the ``result-cache`` owner mirrors ``self.bytes`` exactly,
    so the memory dashboard attributes the resident set; reservation
    uses the non-blocking ``try_reserve`` only — a full pool evicts
    our own LRU tail or skips the put, it never stalls a query."""

    def __init__(self, runner, budget_bytes: int, pool=None):
        self.runner = runner
        self.budget_bytes = int(budget_bytes)
        self.pool = pool
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, ResultEntry]" = OrderedDict()
        #: version-blind table_key -> entry keys scanning it
        self._by_table: Dict[tuple, set] = {}
        #: table_key -> write generation (bumped via note_write)
        self._gen: Dict[tuple, int] = {}
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.stale_served = 0
        self.refreshes = 0
        for m in (
            "result_cache.hits",
            "result_cache.misses",
            "result_cache.evictions",
            "result_cache.bytes",
            "result_cache.stale_served",
            "result_cache.refreshes",
        ):
            REGISTRY.counter(m)

    # ------------------------------------------------------------ lookup

    def get(
        self, key: tuple, max_staleness_s: float = 0.0
    ) -> Optional[Tuple[ResultEntry, bool]]:
        """-> (entry, served_stale) on a usable entry, else None.

        Freshness = the per-table write generations captured at put
        still current (the ``_invalidate_table_caches`` fan-in bumps
        them on every write) AND a re-pin of each scanned table still
        resolves to the pinned snapshot (catches ingest commits a
        peer process minted). A stale entry within
        ``max_staleness_s`` of the instant it went stale serves
        anyway (tier c; the caller spawns the background refresh); a
        staler one drops and the read misses."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                # generation compare covers UNVERSIONED tables only:
                # a snapshot-pinned table invalidates by snapshot-id
                # compare below instead (precise, and durable across
                # processes via the manifest chain) — the process-
                # local generation counter neither survives restart
                # nor sees a peer coordinator's commits, while the
                # re-pinned snapshot id does both
                gen_ok = all(
                    g == self._gen.get(tk, 0)
                    for (tk, s), g in zip(entry.vector, entry.gens)
                    if s is None
                )
        if entry is None:
            self._miss()
            return None
        fresh = gen_ok and self._snapshots_current(entry)
        now = time.time()
        if fresh:
            with self._lock:
                entry.hits += 1
            self.hits += 1
            REGISTRY.counter("result_cache.hits").update()
            return entry, False
        if entry.stale_at == 0.0:
            # first observation of staleness (re-pin mismatch the
            # write fan-in never saw): start the bounded-stale clock
            with self._lock:
                if entry.stale_at == 0.0:
                    entry.stale_at = now
        if max_staleness_s > 0 and now - entry.stale_at <= max_staleness_s:
            with self._lock:
                entry.hits += 1
            self.stale_served += 1
            REGISTRY.counter("result_cache.stale_served").update()
            return entry, True
        self._drop(key)
        self._miss()
        return None

    def _miss(self) -> None:
        self.misses += 1
        REGISTRY.counter("result_cache.misses").update()

    def _snapshots_current(self, entry: ResultEntry) -> bool:
        """Re-pin every scanned table and compare against the vector
        pinned at plan time. Unknown catalogs / probe errors read as
        stale (fail open to re-execution, never to a stale serve)."""
        try:
            from presto_tpu.connectors.spi import TableHandle

            for tk, snap in entry.vector:
                conn = self.runner.catalogs.get(tk[0])
                if conn is None:
                    return False
                cur = conn.pin_snapshot(TableHandle(*tk)).snapshot
                if cur != snap:
                    return False
            return True
        except Exception:
            return False

    # -------------------------------------------------------------- put

    def put(self, key: tuple, stmt, columns, rows, handles) -> bool:
        """Insert/replace the entry for ``key`` (idempotent: N
        microbatch members of one hot fingerprint re-putting the same
        result is a cheap replace). Skips (False) when any scanned
        catalog is non-cacheable, the result exceeds the per-entry
        cap, or the pool cannot cover the bytes even after evicting
        our own tail."""
        vector = snapshot_vector(handles, self.runner.catalogs)
        if vector is None or key is None:
            return False
        rows = [list(r) for r in rows]
        nbytes = _estimate_nbytes(columns, rows)
        if nbytes > max(self.budget_bytes // _MAX_ENTRY_FRACTION, 1):
            return False
        with self._lock:
            old = self._entries.get(key)
            if old is not None and old.stale_at == 0.0 and not old.refreshing:
                # a fresh entry is already resident (a concurrent
                # member of the same microbatch put it): keep it
                return True
            entry = ResultEntry(
                key=key,
                stmt=stmt,
                columns=tuple(columns),
                rows=rows,
                vector=vector,
                gens=tuple(self._gen.get(tk, 0) for tk, _s in vector),
                nbytes=nbytes,
                created_at=time.time(),
                snapshot_label=_snapshot_label(vector),
            )
            if old is not None:
                self._drop_locked(key)
            while (
                self.bytes + nbytes > self.budget_bytes and self._entries
            ):
                self._evict_lru_locked()
            if self.bytes + nbytes > self.budget_bytes:
                return False
            while self.pool is not None and not self.pool.try_reserve(
                "result-cache", nbytes
            ):
                if not self._entries:
                    return False
                self._evict_lru_locked()
            self._entries[key] = entry
            self.bytes += nbytes
            REGISTRY.counter("result_cache.bytes").update(nbytes)
            for tk, _s in vector:
                self._by_table.setdefault(tk, set()).add(key)
        return True

    # ----------------------------------------------------- invalidation

    def note_write(self, handle) -> None:
        """Write-path fan-in (``_invalidate_table_caches``): bump the
        table's write generation and mark every entry scanning it
        STALE — the bounded-staleness serve may still answer from it
        within the session bound; anything else re-executes."""
        tk = handle.table_key
        now = time.time()
        with self._lock:
            self._gen[tk] = self._gen.get(tk, 0) + 1
            for key in self._by_table.get(tk, ()):
                entry = self._entries.get(key)
                if entry is not None and entry.stale_at == 0.0:
                    entry.stale_at = now

    #: the coordinator's audited alias at the invalidation seam
    invalidate = note_write

    def _drop(self, key: tuple) -> None:
        with self._lock:
            self._drop_locked(key)

    def _drop_locked(self, key: tuple) -> None:
        entry = self._entries.pop(key, None)
        if entry is None:
            return
        self.bytes -= entry.nbytes
        REGISTRY.counter("result_cache.bytes").update(-entry.nbytes)
        if self.pool is not None:
            self.pool.release("result-cache", entry.nbytes)
        for tk, _s in entry.vector:
            keys = self._by_table.get(tk)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    self._by_table.pop(tk, None)

    def _evict_lru_locked(self) -> None:
        key = next(iter(self._entries))
        self._drop_locked(key)
        self.evictions += 1
        REGISTRY.counter("result_cache.evictions").update()

    def clear(self) -> None:
        with self._lock:
            for key in list(self._entries):
                self._drop_locked(key)

    # ------------------------------------------------ refresh bookkeeping

    def claim_refresh(self, entry: ResultEntry) -> bool:
        """CAS the per-entry refresh flag: True = the caller owns the
        (single) background refresh of this entry."""
        with self._lock:
            if entry.refreshing:
                return False
            entry.refreshing = True
            return True

    def finish_refresh(self, entry: ResultEntry) -> None:
        with self._lock:
            entry.refreshing = False
        self.refreshes += 1
        REGISTRY.counter("result_cache.refreshes").update()

    # ----------------------------------------------------------- surface

    def stats(self) -> dict:
        """The ``result.cache`` row of system.runtime.caches."""
        with self._lock:
            entries = len(self._entries)
        return {
            "entries": entries,
            "bytes": self.bytes,
            "budget_bytes": self.budget_bytes,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "stale_served": self.stale_served,
            "refreshes": self.refreshes,
        }


# ------------------------------------------------------ MV-aware rewrite


def _reader_output_name(item, i: int) -> str:
    """The visible column name the planner would give this item
    (plan/planner._item_name discipline) — preserved verbatim on the
    rewritten statement so the client sees identical columns."""
    from presto_tpu.sql import ast

    if item.alias:
        return item.alias
    if isinstance(item.expr, ast.Ident):
        return item.expr.parts[-1]
    return f"_col{i}"


def _match_items(stmt, mv) -> Optional[List[Tuple[str, str]]]:
    """Match every reader select item against an MV query item by
    structural AST equality -> [(mv visible column, reader output
    name), ...] in reader order, or None on any unmatched item."""
    out: List[Tuple[str, str]] = []
    for i, item in enumerate(stmt.items):
        for j, mv_item in enumerate(mv.query.items):
            if item.expr == mv_item.expr:
                out.append(
                    (mv.visible_names[j], _reader_output_name(item, i))
                )
                break
        else:
            return None
    return out


def _shape_matches(stmt, mv, registry) -> bool:
    """The reader is itself an eligible single-table aggregate shape
    over the MV's base, with the SAME filter and grouping."""
    from presto_tpu.sql import ast

    if stmt.ctes or stmt.distinct or stmt.having is not None:
        return False
    if stmt.order_by or stmt.limit is not None:
        return False
    if not isinstance(stmt.from_, ast.TableRef):
        return False
    if registry._resolve(stmt.from_.parts) != tuple(mv.base.table_key):
        return False
    if stmt.where != mv.query.where:
        return False
    if sorted(map(repr, stmt.group_by)) != sorted(
        map(repr, mv.query.group_by)
    ):
        return False
    # every reader item must be a grouped column or an eligible
    # aggregate (structural match against the MV items proves it, but
    # an aggregate the MV does not maintain must not half-match)
    return True


def _freshness_gate(registry, mv) -> bool:
    """The ``mview.max-staleness-s`` read-gate discipline, applied to
    a reader who never NAMED the view: a provably-current view always
    rewrites; a stale one rewrites only under an explicit gate —
    within the bound as-is (the same bounded staleness named reads
    get), beyond it after a full refresh. Gate off + stale = NO
    rewrite (the base-table reader did not opt into staleness).
    Dirty views (a failed incremental merge) never rewrite."""
    if mv.dirty or not mv.eligible:
        return False
    if registry._epoch(mv.base) <= mv.state_epoch:
        return True
    max_s = registry.max_staleness_s
    if max_s is None or max_s <= 0:
        return False
    if time.time() - mv.last_refresh_ts <= max_s:
        return True
    try:
        registry.refresh_view(mv, mode="full")
    except Exception:
        return False
    return True


def mview_rewrite(stmt, registry, session):
    """Tier (b): rewrite an eligible aggregate SELECT over a base
    table onto a registered materialized view maintaining exactly that
    shape. -> (rewritten Select, MViewDef) or None (no candidate, no
    match, or freshness gate closed). Never raises."""
    from presto_tpu.sql import ast

    try:
        if registry is None or not registry:
            return None
        if not isinstance(stmt, ast.Select):
            return None
        for mv in list(registry._defs.values()):
            if not mv.eligible:
                continue
            if not _shape_matches(stmt, mv, registry):
                continue
            cols = _match_items(stmt, mv)
            if cols is None:
                continue
            if not _freshness_gate(registry, mv):
                continue
            REGISTRY.counter("result_cache.mview_rewrites").update()
            rewritten = ast.Select(
                items=tuple(
                    ast.SelectItem(ast.Ident((vis,)), alias=out)
                    for vis, out in cols
                ),
                from_=ast.TableRef(mv.parts),
            )
            return rewritten, mv
        return None
    except Exception:
        return None
