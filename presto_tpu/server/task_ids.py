"""Task/attempt identity — the ONE audited module for constructing and
parsing task ids.

Reference parity: fault-tolerant execution (Trino "Project Tardigrade")
keys spooled exchange data by *task attempt*: a logical task may run as
several attempts (retry, speculation), and recovery is only correct
when exactly one attempt's output is consumed. That property hangs on
the id scheme, so construction and parsing live here and nowhere else
(``tools/check_attempt_ids.py`` enforces it — an ad-hoc string split on
a task id elsewhere would silently break attempt dedup).

Format::

    {query_id}.{kind}.{seq}.a{attempt}

- ``query_id``  — the coordinator's query id (no dots, e.g. ``q_c7``)
- ``kind``      — the stage flavor that minted the task (constants below)
- ``seq``       — per-query monotonic sequence number: the LOGICAL task
- ``attempt``   — 0 for the first launch; retries/speculative backups of
  the same logical task bump it and change NOTHING else

``logical_key`` (the id minus the attempt suffix) keys the exchange
spool: every attempt of one logical task spools under the same key, and
consumers (merge tasks, recovery pulls) consume exactly one committed
attempt per key.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

#: stage-flavor tokens (kept substring-compatible with the historical
#: ids: chaos rules and tests match ``.df.`` / ``.merge.`` / ``.join.``)
SOURCE = "t"
PRODUCER = "prod"
MERGE = "merge"
JOIN = "join"
DYNFILTER = "df"

_TASK_ID_RE = re.compile(
    r"^(?P<query>[^.]+)\.(?P<kind>[^.]+)\.(?P<seq>\d+)\.a(?P<attempt>\d+)$"
)


@dataclasses.dataclass(frozen=True)
class TaskId:
    """Parsed form of one task-attempt id."""

    query_id: str
    kind: str
    seq: int
    attempt: int

    def __str__(self) -> str:
        return mint(self.query_id, self.kind, self.seq, self.attempt)

    @property
    def logical_key(self) -> str:
        return f"{self.query_id}.{self.kind}.{self.seq}"


def mint(query_id: str, kind: str, seq: int, attempt: int = 0) -> str:
    """Construct a deterministic task-attempt id."""
    if "." in query_id or "." in kind or not kind:
        raise ValueError(
            f"task-id components must be dot-free: {query_id!r}, {kind!r}"
        )
    if seq < 0 or attempt < 0:
        raise ValueError(f"negative seq/attempt: {seq}, {attempt}")
    return f"{query_id}.{kind}.{seq}.a{attempt}"


def parse(task_id: str) -> TaskId:
    t = try_parse(task_id)
    if t is None:
        raise ValueError(f"not a task-attempt id: {task_id!r}")
    return t


def try_parse(task_id: str) -> Optional[TaskId]:
    m = _TASK_ID_RE.match(task_id)
    if m is None:
        return None
    return TaskId(
        query_id=m.group("query"),
        kind=m.group("kind"),
        seq=int(m.group("seq")),
        attempt=int(m.group("attempt")),
    )


def logical_key(task_id: str) -> str:
    """The id minus its attempt suffix — the spool/recovery key shared
    by every attempt of one logical task. Unparseable (hand-written
    test) ids are their own key: no attempts, no dedup needed."""
    t = try_parse(task_id)
    return t.logical_key if t is not None else task_id


def attempt_of(task_id: str) -> int:
    """Attempt number (0 for first launches and unparseable ids)."""
    t = try_parse(task_id)
    return t.attempt if t is not None else 0


def next_attempt(task_id: str) -> str:
    """Id for the replacement attempt of the same logical task."""
    t = parse(task_id)
    return mint(t.query_id, t.kind, t.seq, t.attempt + 1)


#: coordinator query ids carry a per-boot nonce: ``q_c{N}_{hex6}``
#: (see CoordinatorServer._boot — attempt ids minted across restarts
#: sharing one spool must never collide)
_QID_BOOT_RE = re.compile(r"^q_c\d+_([0-9a-f]{6})$")


def boot_of_query(query_id: str) -> str:
    """The coordinator-incarnation nonce baked into a query id, or ""
    for ids without one (embedded-runner ``q_N`` ids, hand-written
    test ids). The worker's orphan reaper keys task liveness on it: a
    task whose minting incarnation stopped heartbeating is orphaned —
    its buffers are held for nobody."""
    m = _QID_BOOT_RE.match(query_id or "")
    return m.group(1) if m is not None else ""
