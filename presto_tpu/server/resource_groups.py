"""Resource groups: weighted-fair admission with per-group limits.

Reference parity: ``presto-resource-group-managers`` file-configured
``ResourceGroup`` trees (SURVEY.md §2.1 "Dispatch/queue": DB- or
file-configured groups with concurrency/memory limits, weighted/fair
queueing). This implementation keeps the reference's observable
semantics on the file-configured path:

- groups declare ``hardConcurrencyLimit``, ``maxQueued``,
  ``softMemoryLimit`` and a scheduling ``weight``;
- selectors map a query's user (regex) to a group; unmatched queries
  take the configured default group;
- a query beyond its group's queue bound is REJECTED, not queued;
- when a slot frees, the next query comes from the eligible group with
  the smallest running/weight ratio (weighted fairness), FIFO within a
  group.

The coordinator composes this with its global admission semaphore: the
manager decides WHICH query runs next and per-group bounds; the global
``max_concurrent_queries`` stays the cluster-wide cap.

Config shape (``etc/resource-groups.json``-style dict):

    {"rootGroups": [
        {"name": "etl", "weight": 3, "hardConcurrencyLimit": 4,
         "maxQueued": 50, "softMemoryLimit": "4GB"},
        {"name": "adhoc", "weight": 1, "hardConcurrencyLimit": 2,
         "maxQueued": 10}],
     "selectors": [{"user": "etl-.*", "group": "etl"}],
     "defaultGroup": "adhoc"}
"""

from __future__ import annotations

import dataclasses
import json
import re
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass
class ResourceGroup:
    """One leaf group's live state."""

    name: str
    weight: int = 1
    hard_concurrency_limit: int = 1 << 30
    max_queued: int = 100
    soft_memory_limit_bytes: Optional[int] = None
    #: QoS admission lane (server/qos.py): higher-priority groups
    #: dequeue strictly first at the coordinator's admission gate, and
    #: may preempt-and-resume running lower-priority queries. Inert
    #: unless qos.enabled (weighted fairness applies within a lane).
    priority: int = 0
    running: int = 0
    queue: deque = dataclasses.field(default_factory=deque)

    @property
    def queued(self) -> int:
        return len(self.queue)


class ResourceGroupManager:
    """Thread-safe weighted-fair admission over a flat group list (the
    reference nests groups; benchmark-relevant semantics — per-group
    caps + weighted fairness between peers — live at one level, so the
    tree is deliberately flat here with the root caps owned by the
    coordinator's global gate)."""

    def __init__(self, spec: Dict):
        self._lock = threading.Lock()
        #: optional hook: group name -> bytes currently reserved by the
        #: group's running queries; a group over its softMemoryLimit is
        #: ineligible for new admissions until usage drops (reference:
        #: softMemoryLimit demotes the group below its peers)
        self.memory_usage_fn: Optional[Callable[[str], int]] = None
        self.groups: Dict[str, ResourceGroup] = {}
        for g in spec.get("rootGroups", []):
            grp = ResourceGroup(
                name=g["name"],
                weight=int(g.get("weight", 1)),
                hard_concurrency_limit=int(
                    g.get("hardConcurrencyLimit", 1 << 30)
                ),
                max_queued=int(g.get("maxQueued", 100)),
                soft_memory_limit_bytes=(
                    _parse_bytes(g["softMemoryLimit"])
                    if "softMemoryLimit" in g
                    else None
                ),
                priority=int(g.get("priority", 0)),
            )
            if grp.weight <= 0:
                raise ValueError(
                    f"resource group {grp.name}: weight must be positive"
                )
            self.groups[grp.name] = grp
        if not self.groups:
            raise ValueError("resource groups config has no rootGroups")
        self._selectors: List[Tuple[re.Pattern, str]] = []
        for s in spec.get("selectors", []):
            if s["group"] not in self.groups:
                raise ValueError(
                    f"selector references unknown group {s['group']!r}"
                )
            self._selectors.append(
                (re.compile(s.get("user", ".*")), s["group"])
            )
        default = spec.get("defaultGroup")
        if default is None:
            default = next(iter(self.groups))
        if default not in self.groups:
            raise ValueError(f"unknown defaultGroup {default!r}")
        self._default = default

    @classmethod
    def from_file(cls, path: str) -> "ResourceGroupManager":
        with open(path) as f:
            return cls(json.load(f))

    def group_of(self, user: str) -> ResourceGroup:
        for rx, name in self._selectors:
            if rx.fullmatch(user or ""):
                return self.groups[name]
        return self.groups[self._default]

    # ------------------------------------------------------------ admission

    def submit(
        self, user: str, start: Callable[[], None]
    ) -> Tuple[str, Optional[str]]:
        """-> ("run"|"queued", group) after calling ``start`` when the
        group has capacity, or ("rejected", message)."""
        with self._lock:
            g = self.group_of(user)
            # fast path only when no older query waits (FIFO within a
            # group: a memory-demoted group's queue must drain first)
            if (
                not g.queue
                and g.running < g.hard_concurrency_limit
                and not self._over_memory(g)
            ):
                g.running += 1
                run_now = True
            elif g.queued >= g.max_queued:
                return (
                    "rejected",
                    f"Query rejected: resource group {g.name} queue is "
                    f"full (maxQueued {g.max_queued})",
                )
            else:
                g.queue.append(start)
                run_now = False
        if run_now:
            start()
            return "run", g.name
        return "queued", g.name

    def finish(self, group_name: str) -> None:
        """A query of ``group_name`` finished: free its slot, then admit
        the next queued query from the eligible group with the smallest
        running/weight ratio (weighted fairness)."""
        with self._lock:
            g = self.groups.get(group_name)
            if g is not None and g.running > 0:
                g.running -= 1
            nxt = self._pick_next()
            if nxt is None:
                return
            grp, start = nxt
            grp.running += 1
        start()

    def _over_memory(self, g: ResourceGroup) -> bool:
        return (
            g.soft_memory_limit_bytes is not None
            and self.memory_usage_fn is not None
            and self.memory_usage_fn(g.name) >= g.soft_memory_limit_bytes
        )

    def _pick_next(self) -> Optional[Tuple[ResourceGroup, Callable]]:
        eligible = [
            g
            for g in self.groups.values()
            if g.queue
            and g.running < g.hard_concurrency_limit
            and not self._over_memory(g)
        ]
        if not eligible:
            return None
        g = min(eligible, key=lambda g: (g.running / g.weight, g.name))
        return g, g.queue.popleft()

    # -------------------------------------------------------------- stats

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return [
                {
                    "name": g.name,
                    "weight": g.weight,
                    "priority": g.priority,
                    "running": g.running,
                    "queued": g.queued,
                    "hardConcurrencyLimit": g.hard_concurrency_limit,
                    "maxQueued": g.max_queued,
                }
                for g in self.groups.values()
            ]

    def memory_limit_of(self, user: str) -> Optional[int]:
        return self.group_of(user).soft_memory_limit_bytes


def _parse_bytes(s: str) -> int:
    from presto_tpu.utils.memory import parse_bytes

    return parse_bytes(s)
