"""Multi-host runtime: coordinator / worker processes over HTTP.

Reference parity: the coordinator<->worker split of SURVEY.md §1
(L0/L3/L4) and the §2.5 communication backend — REST control plane
(task create/update/status) + pull-based, token-acked paged data plane.

TPU-first shape: one *worker process per host*; each worker executes
plan fragments over its own local device mesh (shard_map + ICI
collectives inside, exactly the in-slice engine), and only host-level
traffic — fragment specs, split assignments, result pages — crosses
processes (the DCN tier). The coordinator runs planning, split
scheduling, partial/final aggregation splitting, the exchange client,
and the host root stage.
"""

from presto_tpu.server.client import PrestoTpuClient  # noqa: F401
from presto_tpu.server.coordinator import CoordinatorServer  # noqa: F401
from presto_tpu.server.worker import WorkerServer  # noqa: F401
