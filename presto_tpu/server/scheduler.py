"""Cross-host query scheduling: fragment -> per-worker tasks.

Reference parity: ``SqlQueryScheduler`` / ``SqlStageExecution`` — a leaf
stage is N tasks over assigned splits of the partitioned source,
intermediate data flows through exchanges, the root stage gathers
(SURVEY.md §2.1 "Query scheduler", §3.2).

TPU-first shape:
- ONE source-partitioned stage per distributable fragment: a scan is
  split by row ranges across workers; every other scan is replicated
  (each worker scans it fully — the reference's REPLICATED build-side
  choice, SURVEY.md §2.4).
- The partitioned scan must reach the stage cut through row-distributive
  edges only: filters, projections, and the *streamed/probe* side of
  joins (the preserved side of outer joins). Concatenating per-worker
  results is only correct when each input row's contribution is
  independent of the partition — the reference encodes the same rule by
  hash-partitioning the probe side and broadcasting the build side.
- The stage is CUT at the lowest aggregation/distinct above the
  partitioned scan: workers run the PARTIAL step, the coordinator runs
  the FINAL merge (via the same ``split_aggregation`` rewrite the
  in-slice engine uses) and then everything above the cut (which may
  include further joins/aggregations over full gathered data).
- If no scan admits a valid partitioning, ``plan_stage`` returns None
  and the coordinator executes the fragment locally (correctness first;
  the reference similarly falls back to single-task stages).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from presto_tpu.parallel.agg_split import split_aggregation
from presto_tpu.plan import nodes as N


@dataclasses.dataclass(frozen=True)
class StagePlan:
    """One distributable fragment scheduled across workers."""

    worker_fragment: N.PlanNode  # runs on every worker over its splits
    final_root: N.PlanNode  # coordinator plan over the RemoteSourceNode
    partition_scan: int  # walk index (in worker_fragment) of split scan
    partition_rows: int  # total row count of the partitioned table


def plan_stage(
    fragment_root: N.PlanNode,
    catalogs,
    replicated_limit: Optional[int] = None,
) -> Optional[StagePlan]:
    """Decompose one distributable fragment into worker/final steps.

    Tries candidate partition scans largest-first; returns None when no
    scan can be partitioned without changing semantics (the coordinator
    then runs the fragment locally).

    ``replicated_limit`` (streaming use): reject a candidate whose
    worker fragment would replicate another scan bigger than this —
    the streamed batch runner stages replicated scans whole, so an
    oversized one must instead be the partition scan of an *earlier*
    recursion step (exec.streaming resolves big-probe-over-big-build
    plans inner-fragment-first this way).
    """
    scans = [
        n for n in N.walk(fragment_root) if isinstance(n, N.TableScanNode)
    ]
    sized: List[Tuple[int, N.TableScanNode]] = []
    for s in scans:
        conn = catalogs.get(s.handle.catalog)
        stats = conn.metadata().get_table_stats(s.handle)
        sized.append((int(stats.row_count or 0), s))
    sized.sort(key=lambda t: -t[0])

    for rows, scan in sized:
        stage = _try_cut(fragment_root, scan, rows)
        if stage is None:
            continue
        if replicated_limit is not None:
            others = [
                r
                for r, s in sized
                if s is not scan
                and any(
                    n is s for n in N.walk(stage.worker_fragment)
                )
            ]
            if any(r > replicated_limit for r in others):
                continue
        return stage
    return None


def _path_to(root: N.PlanNode, target: N.PlanNode) -> Optional[list]:
    """Node path root->...->target by identity, or None."""
    if root is target:
        return [root]
    for c in root.children():
        sub = _path_to(c, target)
        if sub is not None:
            return [root] + sub
    return None


def _edge_distributive(parent: N.PlanNode, child: N.PlanNode) -> bool:
    """True when partitioning ``child``'s rows and concatenating
    ``parent``'s per-partition outputs equals running ``parent`` whole.
    """
    if isinstance(parent, (N.FilterNode, N.ProjectNode)):
        return True
    if isinstance(parent, N.JoinNode):
        if parent.join_type == "inner":
            return True  # inner join distributes over either side
        # semi/anti/left preserve the LEFT (probe) side only
        return child is parent.left
    if isinstance(parent, N.CrossJoinNode):
        # right side is a broadcast scalar; only the left streams
        return child is parent.left
    return False


def _try_cut(
    fragment_root: N.PlanNode, scan: N.TableScanNode, rows: int
) -> Optional[StagePlan]:
    path = _path_to(fragment_root, scan)
    if path is None:
        return None

    # lowest aggregation/distinct above the scan = the stage cut
    cut_i = None
    for i in range(len(path) - 2, -1, -1):
        if isinstance(path[i], (N.AggregationNode, N.DistinctNode)):
            cut_i = i
            break
    # every edge from the scan up to (but not including) the cut must be
    # row-distributive; with no cut, every edge up to the root
    lowest_parent = cut_i + 1 if cut_i is not None else 0
    for i in range(len(path) - 1, lowest_parent, -1):
        if not _edge_distributive(path[i - 1], path[i]):
            return None

    if cut_i is None:
        worker_root = fragment_root
        final_root: N.PlanNode = N.RemoteSourceNode(
            fragment_root=worker_root
        )
    else:
        cut = path[cut_i]
        if isinstance(cut, N.AggregationNode):
            try:
                partial_aggs, fkeys, faggs, post = split_aggregation(
                    cut.group_keys, cut.aggs
                )
            except NotImplementedError:
                # un-decomposable aggregate (e.g. array_agg): no
                # distributed cut; the caller falls back to local
                # execution
                return None
            worker_root = dataclasses.replace(cut, aggs=partial_aggs)
            remote = N.RemoteSourceNode(fragment_root=worker_root)
            final_sub: N.PlanNode = N.AggregationNode(
                source=remote,
                group_keys=fkeys,
                aggs=faggs,
                max_groups=cut.max_groups,
            )
            if post:
                final_sub = N.ProjectNode(
                    source=final_sub, projections=post
                )
        else:  # DistinctNode: dedup-of-dedups
            worker_root = cut
            remote = N.RemoteSourceNode(fragment_root=worker_root)
            final_sub = N.DistinctNode(
                source=remote, max_groups=cut.max_groups
            )
        final_root = _replace_on_path(path[:cut_i], cut, final_sub)

    scan_idx = None
    for i, node in enumerate(N.walk(worker_root)):
        if node is scan:
            scan_idx = i
            break
    if scan_idx is None:  # scan above the cut: nothing to partition
        return None
    return StagePlan(
        worker_fragment=worker_root,
        final_root=final_root,
        partition_scan=scan_idx,
        partition_rows=rows,
    )


def _replace_on_path(
    ancestors: list, old: N.PlanNode, new: N.PlanNode
) -> N.PlanNode:
    """Rebuild the ancestor chain with ``old`` (a direct child of the
    last ancestor) swapped for ``new``."""
    for parent in reversed(ancestors):
        changes = {}
        for f in dataclasses.fields(parent):
            if getattr(parent, f.name) is old:
                changes[f.name] = new
        assert changes, "path ancestor does not reference its child"
        new = dataclasses.replace(parent, **changes)
        old = parent
    return new


def stable_workers(workers) -> list:
    """Placement set for gather/merge/join stages under preemptible-
    aware scheduling: these stages hold the only copy of merged state
    (their buffers are NOT spool-backed the way producer partitions
    are), so they belong on stable nodes — preemptibles keep the
    spool-backed shuffle-producer work, where a preemption costs one
    re-servable partition, not a stage re-run. Returns the
    non-preemptible subset when any exists; an all-preemptible pool
    still schedules (recovery, not placement, is the safety net
    there)."""
    stable = [
        w for w in workers if not getattr(w, "preemptible", False)
    ]
    return stable if stable else list(workers)


def select_exchange_transport(
    workers, enabled: bool, schemas=()
) -> str:
    """Transport selection for one partitioned exchange stage — the
    ONE place that decides ICI vs HTTP (the exchange-plane confinement
    rule pins it here; producers and consumers only *honor* the choice
    carried on ``FragmentSpec.ici_slice``).

    Returns the slice id when every candidate worker announces the
    SAME non-empty slice (co-located: one host process driving one
    device mesh — the topology the in-slice exchange segment requires)
    and every exchanged schema is ICI-transportable (fixed-width
    scalar columns; array/map/row keep the serialized wire). Returns
    "" (the HTTP wire) otherwise: mixed slices, unannounced topology,
    a DRAINING peer in the set, an oversized fan-out, or the gate off.
    A DRAINING worker's edges must degrade to HTTP so the
    zero-failure-drain contract holds even for stages planned at the
    drain boundary."""
    from presto_tpu.parallel.exchange import MAX_ICI_PARTS

    if not enabled or not workers:
        return ""
    if len(workers) > MAX_ICI_PARTS:
        return ""
    slices = set()
    for w in workers:
        if getattr(w, "state", "ACTIVE") != "ACTIVE":
            return ""
        slices.add(getattr(w, "slice_id", ""))
    if len(slices) != 1:
        return ""
    (slice_id,) = slices
    if not slice_id:
        return ""
    for schema in schemas:
        for t in schema.values():
            if t.is_array or t.is_map or t.is_row:
                return ""
    return slice_id


def select_exchange_edges(
    workers, enabled: bool, schemas=()
) -> str:
    """Per-EDGE transport selection for one partitioned exchange
    stage — the successor of :func:`select_exchange_transport`'s
    all-or-nothing rule, and like it the ONE place that decides ICI vs
    HTTP (the exchange-plane confinement rule pins selection here).

    Returns the DOMINANT slice: the largest group of ACTIVE workers
    announcing the same non-empty slice id, provided at least two
    workers share it (a single worker has no in-slice peer to exchange
    with) and every exchanged schema is ICI-transportable. Workers
    outside the dominant slice no longer veto the stage — the slice id
    still rides ``FragmentSpec.ici_slice``, and each EDGE settles
    per-worker at run time: a producer whose own slice does not match
    emits on the HTTP lane (``exchange.ici_fallbacks``), and a
    consumer simply misses the segment for that source and pulls HTTP
    — so a lone cross-slice worker rides HTTP on its own edges without
    taxing the co-located pairs. DRAINING/INACTIVE workers are
    excluded from the count (their edges degrade at drain time), but
    do not demote the rest. Ties break deterministically (largest
    count, then lexicographically greatest slice id)."""
    from presto_tpu.parallel.exchange import MAX_ICI_PARTS

    if not enabled or not workers:
        return ""
    if len(workers) > MAX_ICI_PARTS:
        return ""
    counts: dict = {}
    for w in workers:
        if getattr(w, "state", "ACTIVE") != "ACTIVE":
            continue
        sid = getattr(w, "slice_id", "")
        if sid:
            counts[sid] = counts.get(sid, 0) + 1
    if not counts:
        return ""
    best, n = max(counts.items(), key=lambda kv: (kv[1], kv[0]))
    if n < 2:
        return ""
    for schema in schemas:
        for t in schema.values():
            if t.is_array or t.is_map or t.is_row:
                return ""
    return best


def assign_ranges(total_rows: int, n_ranges: int) -> List[Tuple[int, int]]:
    """Contiguous row ranges of the partitioned scan. The coordinator
    over-partitions (n_ranges = workers x split_queue_factor) and lets
    workers drain a shared queue — dynamic split placement."""
    chunk = -(-total_rows // max(n_ranges, 1))
    out = []
    for i in range(n_ranges):
        lo = min(i * chunk, total_rows)
        hi = min((i + 1) * chunk, total_rows)
        out.append((lo, hi))
    return out
