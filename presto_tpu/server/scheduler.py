"""Cross-host query scheduling: fragment -> per-worker tasks.

Reference parity: ``SqlQueryScheduler`` / ``SqlStageExecution`` — a leaf
stage is N tasks over dynamically assigned splits of the partitioned
source, intermediate data flows through exchanges, the root stage
gathers (SURVEY.md §2.1 "Query scheduler", §3.2).

TPU-first shape (round-1 multihost):
- ONE source-partitioned stage per distributable fragment: the scan
  with the largest stats row count is split by row ranges across
  workers; every other scan is replicated (each worker scans it fully —
  the reference's REPLICATED build-side choice, SURVEY.md §2.4).
- Fragments whose root is an aggregation/distinct split into PARTIAL
  (worker) / FINAL (coordinator merge) steps via the same
  ``split_aggregation`` rewrite the in-slice engine uses.
- The coordinator pulls every task's pages (GATHER), concatenates, and
  finishes the plan locally (final agg + any non-distributable top +
  the host root stage).

Worker-to-worker hash repartition (the REPARTITION exchange crossing
hosts) is intentionally absent this round: inside each worker the
slice-level all_to_all already repartitions across its local mesh, and
the cross-host cut is gather-shaped.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from presto_tpu import expr as E
from presto_tpu.parallel.agg_split import split_aggregation
from presto_tpu.plan import nodes as N


@dataclasses.dataclass(frozen=True)
class StagePlan:
    """One distributable fragment scheduled across workers."""

    worker_fragment: N.PlanNode  # runs on every worker over its splits
    final_root: N.PlanNode  # coordinator plan over the RemoteSourceNode
    partition_scan: int  # walk index (in worker_fragment) of split scan
    partition_rows: int  # total row count of the partitioned table


def plan_stage(fragment_root: N.PlanNode, catalogs) -> StagePlan:
    """Decompose one distributable fragment into worker/final steps."""
    worker_root = fragment_root
    remote = N.RemoteSourceNode(fragment_root=fragment_root)

    if isinstance(fragment_root, N.AggregationNode) and fragment_root.aggs:
        partial_aggs, fkeys, faggs, post = split_aggregation(
            fragment_root.group_keys, fragment_root.aggs
        )
        worker_root = dataclasses.replace(fragment_root, aggs=partial_aggs)
        remote = N.RemoteSourceNode(fragment_root=worker_root)
        final: N.PlanNode = N.AggregationNode(
            source=remote,
            group_keys=fkeys,
            aggs=faggs,
            max_groups=fragment_root.max_groups,
        )
        if post:
            final = N.ProjectNode(source=final, projections=post)
    elif isinstance(fragment_root, N.DistinctNode):
        # distinct-of-distinct: worker dedups its shard, final dedups
        final = N.DistinctNode(
            source=remote, max_groups=fragment_root.max_groups
        )
        worker_root = fragment_root
    else:
        final = remote

    scan_idx, rows = _pick_partition_scan(worker_root, catalogs)
    return StagePlan(
        worker_fragment=worker_root,
        final_root=final,
        partition_scan=scan_idx,
        partition_rows=rows,
    )


def _pick_partition_scan(root: N.PlanNode, catalogs) -> Tuple[int, int]:
    """Walk index + row count of the scan to shard across workers (the
    largest table by connector stats — the probe side in practice)."""
    best_idx, best_rows = -1, -1
    for i, node in enumerate(N.walk(root)):
        if not isinstance(node, N.TableScanNode):
            continue
        conn = catalogs.get(node.handle.catalog)
        stats = conn.metadata().get_table_stats(node.handle)
        rows = int(stats.row_count or 0)
        if rows > best_rows:
            best_idx, best_rows = i, rows
    if best_idx < 0:
        raise ValueError("fragment has no table scan to partition")
    return best_idx, best_rows


def assign_ranges(total_rows: int, n_workers: int) -> List[Tuple[int, int]]:
    """Contiguous row ranges of the partitioned scan, one per worker."""
    chunk = -(-total_rows // max(n_workers, 1))
    out = []
    for i in range(n_workers):
        lo = min(i * chunk, total_rows)
        hi = min((i + 1) * chunk, total_rows)
        out.append((lo, hi))
    return out
