"""Cluster memory arbiter: the distributed half of the memory manager.

Reference parity: Presto's ``ClusterMemoryManager`` + low-memory killer
(PAPER.md layer map; SURVEY.md §2.1 "Memory manager"). Every node's
``MemoryPool`` stays process-local for *enforcement of its own limit*;
this module folds the per-node accounting the workers report on their
announce/status heartbeats (current + peak + blocked reservations +
host-spill occupancy) into ONE cluster view and applies cluster-level
policy:

- ``query.max-memory`` — a query's CLUSTER-WIDE reservation cap;
- ``query.max-memory-per-node`` — the same key that sizes each node
  pool, re-checked per (query, node) so a single-node hog is caught
  even when the node total stays under its limit;
- distributed resource-group quotas — the coordinator's
  ``_group_memory`` hook sums this view, so ``softMemoryLimit``
  finally sees worker-side bytes;
- an admission high-water mark — while the cluster's query-attributed
  usage exceeds ``memory.admission-high-water`` (fraction of the
  cluster's pooled capacity), QUEUED queries are HELD, never failed,
  releasing at ``memory.admission-low-water`` (hysteresis);
- the low-memory killer — when any node reports a reservation blocked
  past ``memory.blocked-timeout-s``, a victim is chosen by the
  pluggable policy (``total-reservation`` = largest cluster-wide
  holder, ``last-admitted`` = newest running query) and killed
  cluster-wide with a ``MEMORY_PRESSURE`` error naming victim and
  policy; under ``retry_policy=QUERY`` the victim re-runs after
  pressure subsides, within the ``query_retry_count`` budget.

The arbiter is a pure accounting/policy engine: observation updates
state and COMPUTES decisions; all side effects (task cancellation,
journaling, re-admission) run through the coordinator's hooks
(`_apply_memory_kill`, `_readmit_memory_victim`). Gated end-to-end by
``memory.governance-enabled`` — disabled, it still folds reports (the
resource-group fix and ``system.runtime.memory`` stay live) but never
holds, never kills, never spills.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from presto_tpu.utils.memory import parse_bytes
from presto_tpu.utils.metrics import REGISTRY

log = logging.getLogger("presto_tpu.memory_arbiter")

#: a node report older than this is dropped from the cluster view
#: (matches the coordinator's discovery TTL)
REPORT_TTL_S = 10.0

#: victim policies the killer understands
KILL_POLICIES = ("total-reservation", "last-admitted")

#: kill decisions retained for system.runtime.memory
MAX_DECISIONS = 100


class ClusterMemoryArbiter:
    """Folds per-node heartbeat memory reports into a cluster view and
    drives the cluster-level memory policy through coordinator hooks."""

    def __init__(self, coord, config=None):
        get = (lambda k, d=None: config.get(k, d)) if config else (
            lambda k, d=None: d
        )
        self.coord = coord
        self.enabled = bool(get("memory.governance-enabled", False))
        mm = get("query.max-memory")
        #: cluster-wide per-query cap (None = unbounded)
        self.max_query_bytes: Optional[int] = (
            parse_bytes(mm) if mm is not None else None
        )
        #: per-(query, node) cap — the same tier-1 key that sizes the
        #: node pools, re-checked against per-query node reservations
        self.max_query_node_bytes: int = parse_bytes(
            get("query.max-memory-per-node") or "8GB"
        )
        self.high_water = float(get("memory.admission-high-water", 0.85))
        lw = get("memory.admission-low-water")
        self.low_water = (
            float(lw) if lw is not None else self.high_water * 0.9
        )
        self.blocked_timeout_s = float(
            get("memory.blocked-timeout-s", 1.0)
        )
        self.kill_policy = str(
            get("memory.kill-policy", "total-reservation")
        )
        if self.kill_policy not in KILL_POLICIES:
            raise ValueError(
                f"memory.kill-policy must be one of {KILL_POLICIES}, "
                f"got {self.kill_policy!r}"
            )
        self._lock = threading.Lock()
        #: node_id -> {"ts", "limit", "reserved", "queries",
        #:             "blocked", "spilled_bytes"}
        self._reports: Dict[str, dict] = {}
        #: victims already dispatched (suppresses duplicate kills while
        #: heartbeats still show the dying query's reservations)
        self._killed: set = set()
        #: admission hold latch (hysteresis)
        self._held = False
        #: wall-clock of the last killer decision: blockage that BEGAN
        #: before it is stale evidence (the kill's cancellations may
        #: not have reached the reporting node yet) and must not pick
        #: a second victim
        self._last_kill_ts = 0.0
        #: kill decisions, newest last (system.runtime.memory rows)
        self.decisions: deque = deque(maxlen=MAX_DECISIONS)
        #: multi-coordinator hook (server/lease.py plane): returns
        #: peer coordinators' LOCAL-pool reports keyed by a synthetic
        #: node id — folded into the cluster view so admission
        #: high-water and capacity are cluster-wide across N
        #: admitters. None (the default) = single-coordinator view,
        #: bit-exact pre-HA. Worker-side bytes are NOT re-folded here:
        #: workers heartbeat every coordinator directly, so each
        #: arbiter already holds them once.
        self.peer_reports_fn = None

    # ---------------------------------------------------------- accounting

    def observe(self, node_id: str, report: Optional[dict]) -> None:
        """Fold one node's heartbeat memory report in; with governance
        enabled, run enforcement against the refreshed view."""
        if not report:
            return
        with self._lock:
            self._reports[node_id] = {
                "ts": time.time(),
                "limit": int(report.get("limit", 0)),
                "reserved": int(report.get("reserved", 0)),
                "queries": dict(report.get("queries") or {}),
                "blocked": list(report.get("blocked") or ()),
                "spilled_bytes": int(report.get("spilled_bytes", 0)),
            }
        if self.enabled:
            self._enforce()

    def forget_query(self, qid: str) -> None:
        """Clear the killed-victim latch (a re-admitted victim may be
        chosen again if it blows up twice)."""
        with self._lock:
            self._killed.discard(qid)

    def suspend_release(self, qid: str) -> None:
        """A QoS suspension (server/qos.py) released this query's
        cluster reservation: drop its entries from the cached per-node
        reports NOW — admission-hold and quota math must stop charging
        a parked query immediately, not a heartbeat later. The
        victim's still-draining tasks re-assert whatever they actually
        hold on their next heartbeats, so accounting converges on
        truth either way."""
        with self._lock:
            for rep in self._reports.values():
                rep["queries"].pop(qid, None)

    def _live_reports(self) -> Dict[str, dict]:
        now = time.time()
        with self._lock:
            return {
                n: r
                for n, r in self._reports.items()
                if now - r["ts"] <= REPORT_TTL_S
            }

    def _local_report(self) -> dict:
        """The coordinator's own pool folded as one more node — the
        same ``rollup_query_report`` fold the workers apply to their
        heartbeats, so attribution can never disagree across tiers."""
        from presto_tpu.exec.staging import SplitCache
        from presto_tpu.utils.memory import rollup_query_report

        cache = getattr(self.coord.local, "split_cache", None)
        rep = rollup_query_report(
            self.coord.memory_pool.snapshot(),
            SplitCache.OWNER,
            cache.spill_used_bytes() if cache is not None else 0,
        )
        rep["ts"] = time.time()
        return rep

    def _view(self) -> Dict[str, dict]:
        """Live per-node reports, coordinator included — and, with
        the multi-coordinator lease plane on, every live PEER
        coordinator's local-pool report (their lease payloads), so
        admission water marks gate against the whole cluster's
        query-attributed bytes and pooled capacity."""
        view = self._live_reports()
        view["coordinator"] = self._local_report()
        if self.peer_reports_fn is not None:
            try:
                for node, rep in (self.peer_reports_fn() or {}).items():
                    if (
                        isinstance(rep, dict)
                        and "limit" in rep
                        and isinstance(rep.get("queries"), dict)
                    ):
                        view.setdefault(node, rep)
            except Exception:
                pass  # a torn peer read must never stall admission
        return view

    def local_report(self) -> dict:
        """Public form of the coordinator-local fold — what this
        coordinator publishes in its own lease payload for PEER
        arbiters to fold (the mirror of ``peer_reports_fn``)."""
        return self._local_report()

    def query_bytes(self, qid: str) -> Tuple[int, int]:
        """(current, peak) WORKER-side bytes of one query — remote
        reports only, so callers that already see the coordinator's
        local pool can add it without double counting."""
        cur = peak = 0
        for rep in self._live_reports().values():
            q = rep["queries"].get(qid)
            if q:
                cur += int(q.get("bytes", 0))
                peak += int(q.get("peak", q.get("bytes", 0)))
        return cur, peak

    def queries_bytes(self, qids) -> int:
        """Summed WORKER-side current bytes of a set of queries (the
        resource-group quota hook adds coordinator-local bytes
        itself)."""
        want = set(qids)
        total = 0
        for rep in self._live_reports().values():
            for qid, q in rep["queries"].items():
                if qid in want:
                    total += int(q.get("bytes", 0))
        return total

    def cluster_usage(self) -> Tuple[int, int]:
        """(query-attributed bytes, pooled capacity) across live
        nodes. Query-attributed only: droppable cache bytes must not
        wedge admission shut with zero queries running."""
        used = limit = 0
        for rep in self._view().values():
            limit += rep["limit"]
            used += sum(
                int(q.get("bytes", 0))
                for q in rep["queries"].values()
            )
        return used, limit

    # ----------------------------------------------------------- admission

    def admission_held(self) -> bool:
        """Hysteresis latch: holds while usage/capacity exceeds the
        high-water mark, releases below the low-water mark. QUEUED
        queries wait — they are never failed by this gate."""
        if not self.enabled or self.high_water <= 0:
            return False
        used, limit = self.cluster_usage()
        if limit <= 0:
            return False
        frac = used / limit
        with self._lock:
            if self._held:
                if frac < self.low_water:
                    self._held = False
                    log.info(
                        "admission released: usage %.0f%% below "
                        "low-water %.0f%%",
                        frac * 100, self.low_water * 100,
                    )
            elif frac > self.high_water:
                self._held = True
                REGISTRY.counter("memory.admission_holds").update()
                log.warning(
                    "admission held: usage %.0f%% over high-water "
                    "%.0f%%", frac * 100, self.high_water * 100,
                )
            return self._held

    def pressure_subsided(self) -> bool:
        """Is the cluster calm enough to re-admit a killed victim?
        Below the low-water mark with no reservation still blocked."""
        used, limit = self.cluster_usage()
        if limit > 0 and used / limit >= self.low_water:
            return False
        return not any(
            rep["blocked"] for rep in self._view().values()
        )

    # ------------------------------------------------------------- killer

    def _enforce(self) -> None:
        """Scan the refreshed view for violations and dispatch kill
        decisions through the coordinator (off-thread — enforcement
        runs on heartbeat handler threads)."""
        try:
            decisions = self._decide()
        except Exception:
            log.warning("memory enforcement failed", exc_info=True)
            return
        for victim, policy, reason in decisions:
            threading.Thread(
                target=self.coord._apply_memory_kill,
                args=(victim, policy, reason),
                daemon=True,
            ).start()

    def _decide(self) -> List[Tuple[str, str, str]]:
        """(victim_qid, policy, reason) kill decisions for the current
        view. Pure: no side effects beyond the killed-latch."""
        view = self._view()
        out: List[Tuple[str, str, str]] = []

        def running(qid: str) -> bool:
            q = self.coord.queries.get(qid)
            return (
                q is not None
                and not q.done.is_set()
                and qid not in self._killed
            )

        def claim(qid: str, policy: str, reason: str) -> bool:
            with self._lock:
                if qid in self._killed:
                    return False
                self._killed.add(qid)
            out.append((qid, policy, reason))
            return True

        # 1. per-query quotas: cluster-wide and per-node caps
        totals: Dict[str, int] = {}
        for node, rep in view.items():
            for qid, q in rep["queries"].items():
                b = int(q.get("bytes", 0))
                totals[qid] = totals.get(qid, 0) + b
                if (
                    b > self.max_query_node_bytes
                    and running(qid)
                ):
                    claim(
                        qid,
                        "query.max-memory-per-node",
                        f"{b}B on {node} exceeds "
                        f"query.max-memory-per-node "
                        f"{self.max_query_node_bytes}B",
                    )
        if self.max_query_bytes is not None:
            for qid, b in totals.items():
                if b > self.max_query_bytes and running(qid):
                    claim(
                        qid,
                        "query.max-memory",
                        f"{b}B cluster-wide exceeds query.max-memory "
                        f"{self.max_query_bytes}B",
                    )

        # 2. low-memory killer: a reservation blocked past the timeout
        #    on any node picks a victim by policy. Evidence freshness:
        #    right after a kill, reports snapshotted before its
        #    cancellations landed still show the old blockage — those
        #    must not claim a second victim. A blocked entry counts
        #    when it BEGAN after the last kill, or when the settle
        #    window has passed and it is STILL blocked (the last kill
        #    freed nothing for it — more pressure, next victim).
        settle = max(self.blocked_timeout_s, 0.5)
        for node, rep in view.items():
            over = [
                b
                for b in rep["blocked"]
                if float(b.get("age_s", 0.0)) >= self.blocked_timeout_s
                and (
                    (rep["ts"] - float(b.get("age_s", 0.0)))
                    > self._last_kill_ts
                    or rep["ts"] - self._last_kill_ts > settle
                )
            ]
            if not over:
                continue
            victim = self._pick_victim(totals, over, running)
            if victim is None:
                continue
            blocked_owner = str(over[0].get("owner", ""))
            if claim(
                victim,
                self.kill_policy,
                f"reservation of {over[0].get('bytes', 0)}B for "
                f"{blocked_owner} blocked "
                f"{float(over[0].get('age_s', 0.0)):.1f}s on {node} "
                f"(pool limit {rep['limit']}B, reserved "
                f"{rep['reserved']}B)",
            ):
                with self._lock:
                    self._last_kill_ts = time.time()
        return out

    def _pick_victim(
        self, totals: Dict[str, int], blocked: List[dict], running
    ) -> Optional[str]:
        """Victim by policy among RUNNING queries. Falls back to the
        blocked owner itself when no running query holds enough bytes
        to matter — the over-budget requester is then its own victim
        (the legacy local-pool failure, surfaced with cluster
        vocabulary)."""
        #: a holder smaller than the blocked request cannot resolve
        #: the blockage — killing it would free nothing and the killer
        #: would just fire again (innocent-bystander protection)
        need = max(
            (int(b.get("bytes", 0)) for b in blocked), default=0
        )
        if self.kill_policy == "last-admitted":
            # RUNNING only: a QUEUED query holds nothing and the
            # admission gate's promise is that it is never failed
            cands = [
                (q.stats.create_time, qid)
                for qid, q in list(self.coord.queries.items())
                if running(qid) and q.state == "RUNNING"
            ]
            if cands:
                return max(cands)[1]
        else:  # total-reservation
            cands = [
                (b, qid)
                for qid, b in totals.items()
                if b >= max(need, 1) and running(qid)
            ]
            if cands:
                return max(cands)[1]
        for b in blocked:
            owner = str(b.get("owner", "")).split("#", 1)[0]
            if running(owner):
                return owner
        return None

    def record_kill(
        self, victim: str, policy: str, reason: str, nbytes: int
    ) -> None:
        """Retain one applied kill decision for observability (the
        coordinator calls this as it applies the kill)."""
        self.decisions.append(
            {
                "ts": time.time(),
                "query_id": victim,
                "policy": policy,
                "reason": reason,
                "bytes": int(nbytes),
            }
        )
        REGISTRY.counter("memory.queries_killed").update()

    # ------------------------------------------------------ observability

    def view_rows(self) -> List[dict]:
        """system.runtime.memory rows: per-node totals, per-(node,
        query) holders, and the retained kill decisions."""
        rows: List[dict] = []
        for node, rep in sorted(self._view().items()):
            rows.append(
                {
                    "node_id": node,
                    "query_id": "",
                    "state": "BLOCKED" if rep["blocked"] else "OK",
                    "reserved_bytes": rep["reserved"],
                    "peak_bytes": sum(
                        int(q.get("peak", 0))
                        for q in rep["queries"].values()
                    ),
                    "blocked_bytes": sum(
                        int(b.get("bytes", 0)) for b in rep["blocked"]
                    ),
                    "spilled_bytes": rep["spilled_bytes"],
                    "limit_bytes": rep["limit"],
                }
            )
            for qid, q in sorted(rep["queries"].items()):
                rows.append(
                    {
                        "node_id": node,
                        "query_id": qid,
                        "state": "RESERVED",
                        "reserved_bytes": int(q.get("bytes", 0)),
                        "peak_bytes": int(
                            q.get("peak", q.get("bytes", 0))
                        ),
                        "blocked_bytes": 0,
                        "spilled_bytes": 0,
                        "limit_bytes": rep["limit"],
                    }
                )
        for d in list(self.decisions):
            rows.append(
                {
                    "node_id": "<cluster>",
                    "query_id": d["query_id"],
                    "state": f"KILLED ({d['policy']})",
                    "reserved_bytes": d["bytes"],
                    "peak_bytes": d["bytes"],
                    "blocked_bytes": 0,
                    "spilled_bytes": 0,
                    "limit_bytes": 0,
                }
            )
        return rows
