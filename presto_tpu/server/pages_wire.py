"""DCN page serialization: typed columnar buffers + compression + checksum.

Reference parity: ``PagesSerde`` — per-block typed encodings with LZ4
compression and an xxhash checksum on the exchange wire (SURVEY.md §2.5
"Serialization"). Here: raw little-endian typed buffers per column,
adaptively zlib-compressed (stdlib zlib — numpy buffers in, C deflate
underneath; buffers below a size floor or whose sample prefix
compresses poorly ship raw, flagged by a per-buffer ``enc`` header
field defaulting to ``"zlib"``), crc32-checksummed per buffer, with a
JSON header.

Frame layout::

    b"PTP1" | u32 header_len | header_json | buffer_0 | buffer_1 | ...

The header lists per-column metadata (name, type, validity, dictionary
values, buffer sizes and crc32s). Dictionary columns ship ids (int32)
plus their value list in the header — dictionaries are tiny relative to
id vectors, and shipping values keeps the wire self-contained across
processes that never shared a dictionary.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.tpch import DictColumn
from presto_tpu.exec.staging import MaskedColumn
from presto_tpu.server.protocol import decode as _decode_type
from presto_tpu.server.protocol import encode as _encode_type

_MAGIC = b"PTP1"

#: adaptive compression floor: buffers below this ship raw — zlib
#: setup costs more than it saves on tiny buffers
MIN_COMPRESS_BYTES = 512

#: sample prefix compressed to probe compressibility of large buffers
COMPRESS_SAMPLE_BYTES = 4096

#: sample compressed/raw ratio above which the whole buffer ships raw
#: (already-compressed or high-entropy data: deflate would burn CPU on
#: both ends to GROW the payload)
COMPRESS_SAMPLE_RATIO = 0.9


def _encode_buffer(raw: bytes) -> Tuple[bytes, int, str]:
    """Adaptive wire encoding: ``(payload, crc32(raw), enc)`` where
    ``enc`` is ``"zlib"`` or ``"raw"``. Small buffers and buffers whose
    sample prefix compresses poorly skip zlib (metrics:
    ``exchange.compress_skipped``); compressed buffers record the bytes
    saved (``exchange.bytes_saved``). The header's per-buffer ``enc``
    field defaults to ``"zlib"`` when absent, so old frames decode
    unchanged (wire format stays PTP1).

    This is the ONE encoder behind both producer entry points —
    ``page_to_wire_columns`` (device-page serialization) and
    ``payload_to_wire_columns`` (the partitioned-output re-serialize
    path, which slices a page into many small per-partition buffers) —
    so the skip/saved counters read consistently whichever path
    produced the frame. Buffers below the size floor return BEFORE any
    probe logic: the 4KB ratio probe would compress a sample larger
    than the buffer itself, exactly the waste the floor exists to
    avoid."""
    from presto_tpu.utils.metrics import REGISTRY

    crc = zlib.crc32(raw)
    if len(raw) < MIN_COMPRESS_BYTES:
        REGISTRY.counter("exchange.compress_skipped").update()
        return raw, crc, "raw"
    if len(raw) > COMPRESS_SAMPLE_BYTES:
        sample = raw[:COMPRESS_SAMPLE_BYTES]
        ratio = len(zlib.compress(sample, 1)) / len(sample)
        if ratio > COMPRESS_SAMPLE_RATIO:
            REGISTRY.counter("exchange.compress_skipped").update()
            return raw, crc, "raw"
    comp = zlib.compress(raw, 1)
    if len(comp) < len(raw):
        REGISTRY.counter("exchange.bytes_saved").update(
            len(raw) - len(comp)
        )
        return comp, crc, "zlib"
    REGISTRY.counter("exchange.compress_skipped").update()
    return raw, crc, "raw"


def _decode_buffer(payload: bytes, enc: str) -> bytes:
    if enc == "raw":
        return bytes(payload)
    return zlib.decompress(payload)


def serialize_page(
    columns: List[Tuple[str, np.ndarray, Optional[np.ndarray], T.DataType,
                        Optional[tuple]]],
    nrows: int,
) -> bytes:
    """columns: (name, data[:n], valid[:n]|None, dtype, dict_values|None).

    Numeric data must already be in native representation (scaled ints
    for decimals, epoch days for dates, int32 ids for dictionary cols).
    """
    from presto_tpu.exec.staging import ArrayColumn

    header: Dict = {"nrows": nrows, "columns": []}
    buffers: List[bytes] = []
    for name, data, valid, dtype, dict_values in columns:
        if isinstance(data, ArrayColumn):
            # array column: offsets buffer + flat values buffer
            off = np.ascontiguousarray(
                np.asarray(data.offsets, np.int32)
            )
            vals = np.ascontiguousarray(
                np.asarray(data.values)[: int(off[-1]) if len(off) else 0]
            )
            oraw, vraw_ = off.tobytes(), vals.tobytes()
            ocomp, ocrc, oenc = _encode_buffer(oraw)
            vcomp_, vcrc_, venc = _encode_buffer(vraw_)
            col = {
                "name": name,
                "type": _encode_type(dtype),
                "array": True,
                "off_comp_size": len(ocomp),
                "off_raw_size": len(oraw),
                "off_crc32": ocrc,
                "off_enc": oenc,
                "np_dtype": vals.dtype.str,
                "comp_size": len(vcomp_),
                "raw_size": len(vraw_),
                "crc32": vcrc_,
                "enc": venc,
            }
            buffers.append(ocomp)
            buffers.append(vcomp_)
            if valid is not None:
                vraw = np.packbits(
                    np.asarray(valid, dtype=bool)
                ).tobytes()
                vc, vcr, vvenc = _encode_buffer(vraw)
                col["valid_comp_size"] = len(vc)
                col["valid_raw_size"] = len(vraw)
                col["valid_crc32"] = vcr
                col["valid_enc"] = vvenc
                buffers.append(vc)
            if dict_values is not None:
                col["dictionary"] = list(dict_values)
            header["columns"].append(col)
            continue
        data = np.ascontiguousarray(data)
        raw = data.tobytes()
        comp, crc, enc = _encode_buffer(raw)
        col: Dict = {
            "name": name,
            "type": _encode_type(dtype),
            "np_dtype": data.dtype.str,
            "comp_size": len(comp),
            "raw_size": len(raw),
            "crc32": crc,
            "enc": enc,
        }
        buffers.append(comp)
        if valid is not None:
            vraw = np.packbits(np.asarray(valid, dtype=bool)).tobytes()
            vcomp, vcrc, venc = _encode_buffer(vraw)
            col["valid_comp_size"] = len(vcomp)
            col["valid_raw_size"] = len(vraw)
            col["valid_crc32"] = vcrc
            col["valid_enc"] = venc
            buffers.append(vcomp)
        if dict_values is not None:
            col["dictionary"] = list(dict_values)
        header["columns"].append(col)
    hj = json.dumps(header).encode()
    return b"".join(
        [_MAGIC, struct.pack("<I", len(hj)), hj] + buffers
    )


def deserialize_page(buf: bytes):
    """-> (payload {name: ndarray | DictColumn}, schema {name: DataType},
    nrows) — feeds exec.staging.stage_page directly."""
    if buf[:4] != _MAGIC:
        raise ValueError("bad page frame magic")
    (hlen,) = struct.unpack_from("<I", buf, 4)
    header = json.loads(buf[8 : 8 + hlen].decode())
    off = 8 + hlen
    payload: Dict = {}
    schema: Dict[str, T.DataType] = {}
    nrows = header["nrows"]
    for col in header["columns"]:
        if col.get("array"):
            from presto_tpu.exec.staging import ArrayColumn

            ocomp = buf[off : off + col["off_comp_size"]]
            off += col["off_comp_size"]
            oraw = _decode_buffer(ocomp, col.get("off_enc", "zlib"))
            if zlib.crc32(oraw) != col["off_crc32"]:
                raise ValueError(
                    f"offsets checksum mismatch on {col['name']}"
                )
            offsets = np.frombuffer(oraw, np.int32).copy()
            vcomp2 = buf[off : off + col["comp_size"]]
            off += col["comp_size"]
            vraw2 = _decode_buffer(vcomp2, col.get("enc", "zlib"))
            if zlib.crc32(vraw2) != col["crc32"]:
                raise ValueError(
                    f"values checksum mismatch on {col['name']}"
                )
            values = np.frombuffer(
                vraw2, np.dtype(col["np_dtype"])
            ).copy()
            valid = None
            if "valid_comp_size" in col:
                vc = buf[off : off + col["valid_comp_size"]]
                off += col["valid_comp_size"]
                vr = _decode_buffer(vc, col.get("valid_enc", "zlib"))
                if zlib.crc32(vr) != col["valid_crc32"]:
                    raise ValueError(
                        f"validity checksum mismatch on {col['name']}"
                    )
                valid = np.unpackbits(
                    np.frombuffer(vr, np.uint8), count=nrows
                ).astype(bool)
            dtype = _decode_type(col["type"])
            schema[col["name"]] = dtype
            payload[col["name"]] = ArrayColumn(
                offsets=offsets,
                values=values,
                valid=valid,
                dict_values=(
                    tuple(col["dictionary"])
                    if "dictionary" in col
                    else None
                ),
            )
            continue
        comp = buf[off : off + col["comp_size"]]
        off += col["comp_size"]
        raw = _decode_buffer(comp, col.get("enc", "zlib"))
        if len(raw) != col["raw_size"] or zlib.crc32(raw) != col["crc32"]:
            raise ValueError(f"page checksum mismatch on {col['name']}")
        data = np.frombuffer(raw, dtype=np.dtype(col["np_dtype"])).copy()
        valid = None
        if "valid_comp_size" in col:
            vcomp = buf[off : off + col["valid_comp_size"]]
            off += col["valid_comp_size"]
            vraw = _decode_buffer(vcomp, col.get("valid_enc", "zlib"))
            if zlib.crc32(vraw) != col["valid_crc32"]:
                raise ValueError(
                    f"validity checksum mismatch on {col['name']}"
                )
            valid = np.unpackbits(
                np.frombuffer(vraw, dtype=np.uint8), count=nrows
            ).astype(bool)
        dtype = _decode_type(col["type"])
        schema[col["name"]] = dtype
        dict_values = (
            tuple(col["dictionary"]) if "dictionary" in col else None
        )
        if valid is not None:
            # native repr + mask: exact (no Python-value round trip)
            payload[col["name"]] = MaskedColumn(
                data=data, valid=valid, values=dict_values
            )
        elif dict_values is not None:
            payload[col["name"]] = DictColumn(
                ids=data.astype(np.int32), values=dict_values
            )
        else:
            payload[col["name"]] = data
    return payload, schema, nrows


def merge_payloads(
    payloads: List[tuple], schema: Dict[str, T.DataType]
) -> Dict[str, object]:
    """Merge deserialized wire pages ``(payload, schema, nrows)`` from
    many workers into ONE staging payload for ``stage_page``.

    Dictionary-encoded columns need id remapping: each worker built its
    dictionary from the values *it* saw, so id spaces differ across
    payloads. Dictionaries are sorted-unique by construction (order-
    preserving, see connectors.tpch.DictColumn), so the union dictionary
    is the sorted union of values and remapping is a searchsorted.
    """
    from presto_tpu.exec.staging import ArrayColumn

    out: Dict[str, object] = {}
    for name in schema:
        if schema[name].is_array:
            out[name] = _merge_array_parts(
                [p[name] for p, _s, _n in payloads]
            )
            continue
        parts = []  # (data, valid|None, dict_values|None) per payload
        for payload, _schema, nrows in payloads:
            col = payload[name]
            if isinstance(col, MaskedColumn):
                parts.append((col.data, col.valid, col.values))
            elif isinstance(col, DictColumn):
                parts.append((np.asarray(col.ids, np.int32), None,
                              tuple(col.values)))
            else:
                parts.append((np.asarray(col), None, None))
        has_dict = any(v is not None for _, _, v in parts)
        has_valid = any(v is not None for _, v, _ in parts)
        if has_dict:
            union = sorted(set().union(*[
                v if v is not None else () for _, _, v in parts
            ]))
            uarr = np.asarray(union, dtype=object)
            datas, valids = [], []
            for data, valid, values in parts:
                ids = np.asarray(data, np.int64)
                if values:
                    vals = np.asarray(values, dtype=object)
                    remap = np.searchsorted(uarr, vals).astype(np.int64)
                    # clip: padded/NULL slots may carry out-of-range ids
                    ids = remap[np.clip(ids, 0, len(vals) - 1)]
                datas.append(ids.astype(np.int32))
                valids.append(
                    valid
                    if valid is not None
                    else np.ones(len(ids), dtype=bool)
                )
            data = np.concatenate(datas) if datas else np.empty(0, np.int32)
            if has_valid:
                out[name] = MaskedColumn(
                    data=data,
                    valid=np.concatenate(valids),
                    values=tuple(union),
                )
            else:
                out[name] = DictColumn(ids=data, values=np.asarray(union))
        else:
            datas = [np.asarray(d) for d, _, _ in parts]
            data = (
                np.concatenate(datas)
                if datas
                else np.empty(0, schema[name].np_dtype)
            )
            if has_valid:
                valids = [
                    v if v is not None else np.ones(len(d), dtype=bool)
                    for d, v, _ in parts
                ]
                out[name] = MaskedColumn(
                    data=data, valid=np.concatenate(valids)
                )
            else:
                out[name] = data
    return out


def _merge_array_parts(parts: List) -> "object":
    """Concatenate ArrayColumn payload chunks: values concat + offsets
    rebase. String-element dictionaries must agree across chunks
    (cross-dictionary array remap is a guarded gap)."""
    from presto_tpu.exec.staging import ArrayColumn

    dicts = {p.dict_values for p in parts if p.dict_values is not None}
    if len(dicts) > 1:
        raise NotImplementedError(
            "merging array columns with differing element "
            "dictionaries is not supported"
        )
    offsets = [np.zeros(1, np.int32)]
    values = []
    valids = []
    base = 0
    any_valid = any(p.valid is not None for p in parts)
    for p in parts:
        off = np.asarray(p.offsets, np.int32)
        n = max(len(off) - 1, 0)
        offsets.append(off[1:] + base)
        base += int(off[-1]) if len(off) else 0
        values.append(np.asarray(p.values)[: int(off[-1]) if len(off) else 0])
        if any_valid:
            valids.append(
                np.asarray(p.valid, bool)
                if p.valid is not None
                else np.ones(n, bool)
            )
    return ArrayColumn(
        offsets=np.concatenate(offsets),
        values=(
            np.concatenate(values) if values else np.zeros(0)
        ),
        valid=np.concatenate(valids) if any_valid else None,
        dict_values=next(iter(dicts)) if dicts else None,
    )


def page_to_wire_columns(page, fetched_n: Optional[int] = None):
    """Device Page -> serialize_page input, with ONE batched device->host
    fetch (two-phase; see exec.host_ops for the relay rationale)."""
    import jax

    from presto_tpu.exec.staging import ArrayColumn

    n = fetched_n if fetched_n is not None else int(page.num_valid)
    leaves = []
    for blk in page.blocks:
        if blk.offsets is not None:
            # array block: offsets prefix + FULL flat values (live
            # extent is data-dependent; serialize trims to offsets[-1])
            leaves.append(blk.offsets[: n + 1])
            leaves.append(blk.data)
        else:
            leaves.append(blk.data[:n])
        if blk.valid is not None:
            leaves.append(blk.valid[:n])
    fetched = jax.device_get(leaves)
    cols = []
    i = 0
    for name, blk in zip(page.names, page.blocks):
        if blk.offsets is not None:
            offsets = np.asarray(fetched[i])
            i += 1
            values = np.asarray(fetched[i])
            i += 1
            valid = None
            if blk.valid is not None:
                valid = fetched[i]
                i += 1
            cols.append(
                (
                    name,
                    ArrayColumn(offsets=offsets, values=values,
                                valid=valid),
                    valid,
                    blk.dtype,
                    (
                        tuple(blk.dictionary.values)
                        if blk.dictionary is not None
                        else None
                    ),
                )
            )
            continue
        data = fetched[i]
        i += 1
        valid = None
        if blk.valid is not None:
            valid = fetched[i]
            i += 1
        dict_values = (
            tuple(blk.dictionary.values) if blk.dictionary is not None else None
        )
        cols.append((name, data, valid, blk.dtype, dict_values))
    return cols, n


def payload_to_wire_columns(payload, schema, nrows: int):
    """Staging payload (deserialize_page / streaming._page_to_payload
    form) -> serialize_page input. Used by the partitioned-output path:
    producers bucket host-side payloads and re-serialize each
    partition's slice without another device round trip."""
    from presto_tpu.connectors.tpch import DictColumn
    from presto_tpu.exec.staging import ArrayColumn, MaskedColumn

    cols = []
    for name, t in schema.items():
        col = payload[name]
        if isinstance(col, ArrayColumn):
            sliced = col[0:nrows]  # offsets rebase + values trim
            cols.append(
                (name, sliced, sliced.valid, t, sliced.dict_values)
            )
        elif isinstance(col, MaskedColumn):
            values = (
                tuple(col.values) if col.values is not None else None
            )
            cols.append(
                (
                    name,
                    np.asarray(col.data)[:nrows],
                    np.asarray(col.valid)[:nrows],
                    t,
                    values,
                )
            )
        elif isinstance(col, DictColumn):
            cols.append(
                (
                    name,
                    np.asarray(col.ids, np.int32)[:nrows],
                    None,
                    t,
                    tuple(col.values),
                )
            )
        else:
            cols.append((name, np.asarray(col)[:nrows], None, t, None))
    return cols
