"""DCN page serialization: typed columnar buffers + compression + checksum.

Reference parity: ``PagesSerde`` — per-block typed encodings with LZ4
compression and an xxhash checksum on the exchange wire (SURVEY.md §2.5
"Serialization"). Here: raw little-endian typed buffers per column,
zlib-compressed (stdlib zlib — numpy buffers in, C deflate underneath),
crc32-checksummed per buffer, with a JSON header.

Frame layout::

    b"PTP1" | u32 header_len | header_json | buffer_0 | buffer_1 | ...

The header lists per-column metadata (name, type, validity, dictionary
values, buffer sizes and crc32s). Dictionary columns ship ids (int32)
plus their value list in the header — dictionaries are tiny relative to
id vectors, and shipping values keeps the wire self-contained across
processes that never shared a dictionary.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.connectors.tpch import DictColumn
from presto_tpu.exec.staging import MaskedColumn
from presto_tpu.server.protocol import decode as _decode_type
from presto_tpu.server.protocol import encode as _encode_type

_MAGIC = b"PTP1"


def _compress(raw: bytes) -> Tuple[bytes, int]:
    comp = zlib.compress(raw, level=1)
    return comp, zlib.crc32(raw)


def serialize_page(
    columns: List[Tuple[str, np.ndarray, Optional[np.ndarray], T.DataType,
                        Optional[tuple]]],
    nrows: int,
) -> bytes:
    """columns: (name, data[:n], valid[:n]|None, dtype, dict_values|None).

    Numeric data must already be in native representation (scaled ints
    for decimals, epoch days for dates, int32 ids for dictionary cols).
    """
    header: Dict = {"nrows": nrows, "columns": []}
    buffers: List[bytes] = []
    for name, data, valid, dtype, dict_values in columns:
        data = np.ascontiguousarray(data)
        raw = data.tobytes()
        comp, crc = _compress(raw)
        col: Dict = {
            "name": name,
            "type": _encode_type(dtype),
            "np_dtype": data.dtype.str,
            "comp_size": len(comp),
            "raw_size": len(raw),
            "crc32": crc,
        }
        buffers.append(comp)
        if valid is not None:
            vraw = np.packbits(np.asarray(valid, dtype=bool)).tobytes()
            vcomp, vcrc = _compress(vraw)
            col["valid_comp_size"] = len(vcomp)
            col["valid_raw_size"] = len(vraw)
            col["valid_crc32"] = vcrc
            buffers.append(vcomp)
        if dict_values is not None:
            col["dictionary"] = list(dict_values)
        header["columns"].append(col)
    hj = json.dumps(header).encode()
    return b"".join(
        [_MAGIC, struct.pack("<I", len(hj)), hj] + buffers
    )


def deserialize_page(buf: bytes):
    """-> (payload {name: ndarray | DictColumn}, schema {name: DataType},
    nrows) — feeds exec.staging.stage_page directly."""
    if buf[:4] != _MAGIC:
        raise ValueError("bad page frame magic")
    (hlen,) = struct.unpack_from("<I", buf, 4)
    header = json.loads(buf[8 : 8 + hlen].decode())
    off = 8 + hlen
    payload: Dict = {}
    schema: Dict[str, T.DataType] = {}
    nrows = header["nrows"]
    for col in header["columns"]:
        comp = buf[off : off + col["comp_size"]]
        off += col["comp_size"]
        raw = zlib.decompress(comp)
        if len(raw) != col["raw_size"] or zlib.crc32(raw) != col["crc32"]:
            raise ValueError(f"page checksum mismatch on {col['name']}")
        data = np.frombuffer(raw, dtype=np.dtype(col["np_dtype"])).copy()
        valid = None
        if "valid_comp_size" in col:
            vcomp = buf[off : off + col["valid_comp_size"]]
            off += col["valid_comp_size"]
            vraw = zlib.decompress(vcomp)
            if zlib.crc32(vraw) != col["valid_crc32"]:
                raise ValueError(
                    f"validity checksum mismatch on {col['name']}"
                )
            valid = np.unpackbits(
                np.frombuffer(vraw, dtype=np.uint8), count=nrows
            ).astype(bool)
        dtype = _decode_type(col["type"])
        schema[col["name"]] = dtype
        dict_values = (
            tuple(col["dictionary"]) if "dictionary" in col else None
        )
        if valid is not None:
            # native repr + mask: exact (no Python-value round trip)
            payload[col["name"]] = MaskedColumn(
                data=data, valid=valid, values=dict_values
            )
        elif dict_values is not None:
            payload[col["name"]] = DictColumn(
                ids=data.astype(np.int32), values=dict_values
            )
        else:
            payload[col["name"]] = data
    return payload, schema, nrows


def merge_payloads(
    payloads: List[tuple], schema: Dict[str, T.DataType]
) -> Dict[str, object]:
    """Merge deserialized wire pages ``(payload, schema, nrows)`` from
    many workers into ONE staging payload for ``stage_page``.

    Dictionary-encoded columns need id remapping: each worker built its
    dictionary from the values *it* saw, so id spaces differ across
    payloads. Dictionaries are sorted-unique by construction (order-
    preserving, see connectors.tpch.DictColumn), so the union dictionary
    is the sorted union of values and remapping is a searchsorted.
    """
    out: Dict[str, object] = {}
    for name in schema:
        parts = []  # (data, valid|None, dict_values|None) per payload
        for payload, _schema, nrows in payloads:
            col = payload[name]
            if isinstance(col, MaskedColumn):
                parts.append((col.data, col.valid, col.values))
            elif isinstance(col, DictColumn):
                parts.append((np.asarray(col.ids, np.int32), None,
                              tuple(col.values)))
            else:
                parts.append((np.asarray(col), None, None))
        has_dict = any(v is not None for _, _, v in parts)
        has_valid = any(v is not None for _, v, _ in parts)
        if has_dict:
            union = sorted(set().union(*[
                v if v is not None else () for _, _, v in parts
            ]))
            uarr = np.asarray(union, dtype=object)
            datas, valids = [], []
            for data, valid, values in parts:
                ids = np.asarray(data, np.int64)
                if values:
                    vals = np.asarray(values, dtype=object)
                    remap = np.searchsorted(uarr, vals).astype(np.int64)
                    # clip: padded/NULL slots may carry out-of-range ids
                    ids = remap[np.clip(ids, 0, len(vals) - 1)]
                datas.append(ids.astype(np.int32))
                valids.append(
                    valid
                    if valid is not None
                    else np.ones(len(ids), dtype=bool)
                )
            data = np.concatenate(datas) if datas else np.empty(0, np.int32)
            if has_valid:
                out[name] = MaskedColumn(
                    data=data,
                    valid=np.concatenate(valids),
                    values=tuple(union),
                )
            else:
                out[name] = DictColumn(ids=data, values=np.asarray(union))
        else:
            datas = [np.asarray(d) for d, _, _ in parts]
            data = (
                np.concatenate(datas)
                if datas
                else np.empty(0, schema[name].np_dtype)
            )
            if has_valid:
                valids = [
                    v if v is not None else np.ones(len(d), dtype=bool)
                    for d, v, _ in parts
                ]
                out[name] = MaskedColumn(
                    data=data, valid=np.concatenate(valids)
                )
            else:
                out[name] = data
    return out


def page_to_wire_columns(page, fetched_n: Optional[int] = None):
    """Device Page -> serialize_page input, with ONE batched device->host
    fetch (two-phase; see exec.host_ops for the relay rationale)."""
    import jax

    n = fetched_n if fetched_n is not None else int(page.num_valid)
    leaves = []
    for blk in page.blocks:
        leaves.append(blk.data[:n])
        if blk.valid is not None:
            leaves.append(blk.valid[:n])
    fetched = jax.device_get(leaves)
    cols = []
    i = 0
    for name, blk in zip(page.names, page.blocks):
        data = fetched[i]
        i += 1
        valid = None
        if blk.valid is not None:
            valid = fetched[i]
            i += 1
        dict_values = (
            tuple(blk.dictionary.values) if blk.dictionary is not None else None
        )
        cols.append((name, data, valid, blk.dtype, dict_values))
    return cols, n


def payload_to_wire_columns(payload, schema, nrows: int):
    """Staging payload (deserialize_page / streaming._page_to_payload
    form) -> serialize_page input. Used by the partitioned-output path:
    producers bucket host-side payloads and re-serialize each
    partition's slice without another device round trip."""
    from presto_tpu.connectors.tpch import DictColumn
    from presto_tpu.exec.staging import MaskedColumn

    cols = []
    for name, t in schema.items():
        col = payload[name]
        if isinstance(col, MaskedColumn):
            values = (
                tuple(col.values) if col.values is not None else None
            )
            cols.append(
                (
                    name,
                    np.asarray(col.data)[:nrows],
                    np.asarray(col.valid)[:nrows],
                    t,
                    values,
                )
            )
        elif isinstance(col, DictColumn):
            cols.append(
                (
                    name,
                    np.asarray(col.ids, np.int32)[:nrows],
                    None,
                    t,
                    tuple(col.values),
                )
            )
        else:
            cols.append((name, np.asarray(col)[:nrows], None, t, None))
    return cols
