"""Config-file bootstrap: ``etc/`` directory -> running node.

Reference parity: ``PrestoServer`` + the three config tiers of
SURVEY.md §5.6 — tier 1 ``etc/config.properties`` +
``etc/node.properties`` (static node config, unknown keys fail fast),
tier 2 ``etc/catalog/*.properties`` (one connector instance per file,
``connector.name=`` selects the factory), tier 3 session properties
(presto_tpu.session, per-query).

Usage::

    python -m presto_tpu.server.launcher --etc-dir etc/

with ``etc/config.properties`` like::

    coordinator=true
    http-server.port=8080
    query.max-memory-per-node=4GB

    # workers instead set:
    # coordinator=false
    # discovery.uri=http://coordinator-host:8080

and ``etc/catalog/tpch.properties``::

    connector.name=tpch
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
from typing import Dict, Optional, Tuple

from presto_tpu.connectors import create_connector
from presto_tpu.exec.staging import CatalogManager
from presto_tpu.server.pool import WorkerPoolProvider
from presto_tpu.session import NodeConfig


def parse_properties(path: str) -> Dict[str, str]:
    """java-.properties-style ``key=value`` lines; # comments; blank
    lines ignored (reference: airlift config loading)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise ValueError(
                    f"{path}:{lineno}: expected key=value, got {line!r}"
                )
            k, _, v = line.partition("=")
            out[k.strip()] = v.strip()
    return out


def load_etc(etc_dir: str) -> Tuple[NodeConfig, CatalogManager]:
    """etc/ directory -> (node config, mounted catalogs).

    ``config.properties`` is required; ``node.properties`` merges in
    when present; every ``catalog/*.properties`` mounts one connector
    (the file stem is the catalog name)."""
    cfg_path = os.path.join(etc_dir, "config.properties")
    if not os.path.exists(cfg_path):
        raise FileNotFoundError(f"missing {cfg_path}")
    props = parse_properties(cfg_path)
    node_path = os.path.join(etc_dir, "node.properties")
    if os.path.exists(node_path):
        merged = parse_properties(node_path)
        merged.update(props)  # config.properties wins on conflict
        props = merged
    config = NodeConfig(props)  # unknown keys fail fast here

    catalogs = CatalogManager()
    cat_dir = os.path.join(etc_dir, "catalog")
    if os.path.isdir(cat_dir):
        for fn in sorted(os.listdir(cat_dir)):
            if not fn.endswith(".properties"):
                continue
            cat_props = parse_properties(os.path.join(cat_dir, fn))
            cname = cat_props.pop("connector.name", None)
            if cname is None:
                raise ValueError(
                    f"{fn}: catalog file must set connector.name"
                )
            catalog = fn[: -len(".properties")]
            catalogs.register(catalog, create_connector(cname, **cat_props))
    return config, catalogs


class LocalWorkerPoolProvider(WorkerPoolProvider):
    """In-process pool provider: the zero-dependency shape of the
    elastic-pool SPI (server.pool.WorkerPoolProvider). ``spawn``
    starts a WorkerServer thread in THIS process pointed at the
    coordinator; ``drain`` routes through the real drain protocol
    (``PUT /v1/state/drain`` semantics via ``WorkerServer.drain`` on a
    background thread), so scale-down is identical to a rolling
    restart. Real deployments implement the same two methods against
    their scheduler (k8s replicas, GCE MIGs, TPU pod managers) —
    autoscaled capacity defaults to PREEMPTIBLE, which the scheduler
    treats as first-class (spool-backed producers there, gather/merge
    on stable nodes)."""

    def __init__(
        self,
        coordinator_uri: str,
        config=None,
        catalogs=None,
        preemptible: bool = True,
    ):
        self.coordinator_uri = coordinator_uri
        self.config = config
        self.catalogs = catalogs
        self.preemptible = preemptible
        self.workers: Dict[str, object] = {}
        self._lock = threading.Lock()

    def spawn(self) -> str:
        from presto_tpu.server.worker import WorkerServer

        w = WorkerServer(
            coordinator_uri=self.coordinator_uri,
            catalogs=self.catalogs,
            config=self.config,
            preemptible=self.preemptible,
        ).start()
        with self._lock:
            self.workers[w.node_id] = w
        return w.node_id

    def drain(self, node_id: str) -> None:
        with self._lock:
            w = self.workers.pop(node_id, None)
        if w is None:
            return  # already gone (preempted/killed): a no-op drain
        threading.Thread(target=w.drain, daemon=True).start()

    def owns(self, node_id: str) -> bool:
        """Still drainable by this provider: tracked AND not already
        shutting down (a preempted/crashed in-process worker flips
        `_shutting_down`, so the autoscaler may forget it; a worker
        merely slow to announce stays owned)."""
        with self._lock:
            w = self.workers.get(node_id)
        return w is not None and not getattr(w, "_shutting_down", False)


def launch(etc_dir: str):
    """Boot the node this etc/ describes; returns the running server
    (CoordinatorServer or WorkerServer). A coordinator config with
    ``pool.max-workers`` set additionally attaches the local pool
    provider and starts the autoscaler (elastic worker pool)."""
    from presto_tpu.server.coordinator import CoordinatorServer
    from presto_tpu.server.worker import WorkerServer

    config, catalogs = load_etc(etc_dir)
    port = int(config.get("http-server.port", 0) or 0)
    if config.get("coordinator", False):
        # optional weighted-fair resource groups (reference:
        # etc/resource-groups.json file-configured manager)
        rg_path = os.path.join(etc_dir, "resource-groups.json")
        server: object = CoordinatorServer(
            port=port,
            catalogs=catalogs,
            config=config,
            resource_groups=rg_path if os.path.exists(rg_path) else None,
        ).start()
        if int(config.get("pool.max-workers", 0) or 0) > 0:
            server.attach_pool(
                LocalWorkerPoolProvider(
                    server.uri, config=config, catalogs=catalogs
                )
            )
    else:
        disc = config.get("discovery.uri")
        if not disc:
            raise ValueError(
                "worker config requires discovery.uri "
                "(the coordinator's address)"
            )
        server = WorkerServer(
            port=port,
            catalogs=catalogs,
            coordinator_uri=disc,
            node_id=config.get("node.id"),
            config=config,
        ).start()
    return server


def install_signal_handlers(server, exit=sys.exit):
    """SIGTERM/SIGINT -> graceful drain (rolling-restart protocol).

    A worker drains: it stops accepting tasks, announces ``DRAINING``
    (the coordinator stops scheduling to it), finishes + serves/spools
    its running outputs, then exits clean — a rolling restart under
    live load loses zero queries. A PREEMPTIBLE worker treats SIGTERM
    as the preemption notice (``preempt``: the same drain under the
    short ``pool.preempt-grace-s`` window — cloud preemptions don't
    wait out a full drain grace). A coordinator (no ``drain``) falls
    back to its ordinary shutdown. Returns the installed handler so
    tests can invoke and assert it directly."""

    def handler(signum, frame):
        name = signal.Signals(signum).name
        print(f"{name}: draining before exit", flush=True)
        drain = getattr(server, "drain", None)
        if getattr(server, "preemptible", False):
            drain = getattr(server, "preempt", drain)
        try:
            if drain is not None:
                drain()
            else:
                server.shutdown()
        finally:
            exit(0)

    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, handler)
    except ValueError:
        # signal handlers only install from the main thread; an
        # embedded (threaded) launch still gets the drain-aware main
        # loop, just not signal wiring
        pass
    return handler


def main(argv: Optional[list] = None) -> None:
    ap = argparse.ArgumentParser(
        description="presto-tpu node launcher (config-file bootstrap)"
    )
    ap.add_argument("--etc-dir", default="etc")
    args = ap.parse_args(argv)
    server = launch(args.etc_dir)
    kind = type(server).__name__
    print(f"{kind} listening on {server.uri}", flush=True)
    # SIGTERM (rolling restarts) and SIGINT (Ctrl-C during tests) both
    # drain gracefully instead of leaving workers undrained
    install_signal_handlers(server)
    import time

    # exit when the server shuts down, signal or not: a worker drained
    # over HTTP (PUT /v1/state/drain) must end the PROCESS — a rolling
    # restart waits on exactly that, and a sleeping zombie would hang it
    while not getattr(server, "_shutting_down", False):
        time.sleep(0.5)
    print(f"{kind} exited", flush=True)


if __name__ == "__main__":
    main()
