"""Tail-latency QoS plane: priority admission lanes, preempt-and-resume
of analytic queries, and per-group SLO enforcement.

Reference parity: Presto's resource-group/admission machinery is the
layer that keeps interactive traffic alive under mixed load (PAPER.md;
SURVEY.md §2.1 "Dispatch/queue"). At serving scale p99 *is* the
product, and every mechanism this plane needs already exists on the
shelf — weighted-fair resource groups, the drain protocol + spooled
stage recovery, the memory killer's journaled victim policies. This
module composes them:

- **Priority lanes at admission.** Resource groups gain a ``priority``
  (group spec, or ``qos.<group>.priority`` config) and an optional
  latency SLO (``qos.<group>.target-p99-ms``). The coordinator's
  admission path dequeues STRICTLY by lane (higher priority always
  first) with the resource-group weighted-fair rule (smallest
  running/weight ratio) inside a lane.

- **Preempt-and-resume, not kill.** When a higher-priority query
  queues behind running lower-priority work, the controller picks a
  victim (lowest priority first, newest admission first — the mirror
  of the memory killer's last-admitted policy) and SUSPENDS it: the
  victim's stage threads park at the next range boundary (claimed
  ranges run to completion — tasks exit clean, spool-backed producers
  commit their partition output to the ``ExchangeSpool``), the slot
  frees immediately for the interactive lane, the query parks as
  ``SUSPENDED`` with a journal frame recording its spooled progress,
  and its cluster memory reservation releases. Resume re-admits the
  parked query at the FRONT of its own lane (it already held a slot
  once); the stage loop continues with the SAME logical task ids, so
  completed producer attempts are never re-run — a merge task whose
  producer died during the suspension re-serves the committed
  partitions from the spool.

- **Re-suspend hysteresis.** A resumed query is immune to further
  preemption for ``qos.resume-grace-s``, and no query is suspended
  more than ``qos.max-suspensions-per-query`` times — a storm of
  interactive arrivals cannot livelock an analytic query (the
  ``suspend_storm`` fault rule makes exactly this testable).

- **Deadline-aware straggler speculation.** ``speculation_scale``
  tightens the PR 2 straggler threshold as a query approaches its
  group's SLO budget (linear down to a 0.25 floor) — a query about to
  blow its p99 target speculates earlier.

- **Observability.** Per-group p50/p99 reservoir latency quantiles,
  suspension/resume counters, and SLO misses serve as
  ``system.runtime.qos`` and inside ``GET /v1/query`` QueryInfo.

Gated end-to-end by ``qos.enabled=false`` (default): disabled, the
controller is never constructed and the coordinator keeps its
bit-exact legacy admission semaphore.

Confinement (``tools/analyze.py`` rule ``qos-plane``): victim
selection, suspend, and resume live HERE; the coordinator only calls
``qos_admit`` / ``qos_release`` / ``qos_checkpoint`` /
``speculation_scale``, and the journal/arbiter/spool hooks
(``record_suspend`` / ``record_resume`` / ``suspend_release`` /
``committed_for_query``) are reached only from this module.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from presto_tpu.session import NodeConfig
from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY, DistributionStat

log = logging.getLogger("presto_tpu.qos")

#: per-group config keys (qos.<group>.priority / .target-p99-ms) —
#: the ONE pattern NodeConfig validates with, so acceptance and
#: consumption can never drift
_GROUP_KEY = NodeConfig._QOS_GROUP_KEY

#: floor of the deadline-aware speculation tightening — a query past
#: its whole SLO budget still speculates at 1/4 the normal threshold,
#: never at zero (which would speculate every range)
SPECULATION_FLOOR = 0.25


class _QosGroup:
    """One admission lane member: a resource group's QoS state."""

    __slots__ = (
        "name", "priority", "weight", "target_p99_ms", "latency",
        "queue", "running", "queries", "slo_misses", "suspensions",
        "resumes",
    )

    def __init__(self, name: str, priority: int = 0, weight: int = 1):
        self.name = name
        self.priority = int(priority)
        self.weight = max(int(weight), 1)
        self.target_p99_ms: Optional[float] = None
        #: per-group end-to-end latency reservoir (p50/p99 in the
        #: system.runtime.qos view)
        self.latency = DistributionStat()
        #: waiting admissions, FIFO; resume re-entries go to the FRONT
        self.queue: deque = deque()
        self.running = 0
        self.queries = 0
        self.slo_misses = 0
        self.suspensions = 0
        self.resumes = 0


class _QosEntry:
    """One query's admission state. ``event`` doubles as the admission
    gate (``qos_admit`` waits on it) and the resume gate (a suspended
    query's parked stage threads wait on it in ``qos_checkpoint``)."""

    __slots__ = (
        "q", "qid", "group", "state", "event", "seq", "resuming",
        "resume_pending", "effects_done", "suspensions",
        "suspended_ms", "suspend_t0", "last_resume",
    )

    def __init__(self, q, group: _QosGroup):
        self.q = q
        self.qid = q.qid
        self.group = group
        self.state = "WAITING"  # WAITING | RUNNING | SUSPENDED
        self.event = threading.Event()
        self.seq = 0  # admission order (victim pick: newest first)
        #: queued-for-resume (entry sits at its lane's front)
        self.resuming = False
        #: dispatched after a suspension; the first parked thread to
        #: wake finalizes the resume (journal/counters) exactly once
        self.resume_pending = False
        #: suspend side effects (journal frame, memory release) have
        #: been applied: a resume close-out orders itself AFTER this,
        #: so an instant re-dispatch can never journal qos_resume
        #: before qos_suspend or un-suspend a state write in flight
        self.effects_done = threading.Event()
        self.effects_done.set()  # no suspension outstanding
        self.suspensions = 0
        self.suspended_ms = 0.0
        self.suspend_t0 = 0.0
        self.last_resume = 0.0

    @property
    def priority(self) -> int:
        return self.group.priority


class QosController:
    """The coordinator's QoS plane: priority-lane admission +
    preempt-and-resume + per-group SLO accounting. One instance per
    coordinator, constructed only when ``qos.enabled=true``."""

    def __init__(self, coord, config, max_concurrent: int):
        self.coord = coord
        self.slots = max(int(max_concurrent), 1)
        get = (
            (lambda k, d=None: config.get(k, d))
            if config is not None
            else (lambda k, d=None: d)
        )
        #: a resumed query is immune to re-suspension this long
        self.resume_grace_s = float(get("qos.resume-grace-s", 5.0))
        #: lifetime suspension cap per query (0 = never preempt)
        self.max_suspensions = int(
            get("qos.max-suspensions-per-query", 2)
        )
        self._cond = threading.Condition()
        self._groups: Dict[str, _QosGroup] = {}
        #: multi-coordinator hook (server/lease.py plane): returns
        #: {peer_id: {lane: {"running", "queued"}}} from live peer
        #: lease payloads; None (default) = local-only view, bit-exact
        self.peer_lanes_fn = None
        #: qid -> entry, admission through release (suspended included)
        self._entries: Dict[str, _QosEntry] = {}
        self._running: Dict[str, _QosEntry] = {}
        self._seq = itertools.count(1)
        # seed lanes from the resource-group tree (priority may live in
        # the group spec), then apply qos.<group>.* config overrides —
        # a config-named group not in the tree still gets a lane (its
        # selectors just never route there until groups are configured)
        rg = getattr(coord, "resource_groups", None)
        if rg is not None:
            for g in rg.groups.values():
                self._groups[g.name] = _QosGroup(
                    g.name,
                    priority=int(getattr(g, "priority", 0)),
                    weight=g.weight,
                )
        for key, val in (getattr(config, "props", None) or {}).items():
            m = _GROUP_KEY.match(key)
            if m is None:
                continue
            grp = self._group(m.group(1))
            if m.group(2) == "priority":
                grp.priority = int(val)
            else:
                grp.target_p99_ms = float(val)

    # ------------------------------------------------------------ groups

    def _group(self, name: str) -> _QosGroup:
        g = self._groups.get(name)
        if g is None:
            g = self._groups[name] = _QosGroup(name)
        return g

    def group_of(self, q) -> _QosGroup:
        return self._group(
            getattr(q, "resource_group", None) or "default"
        )

    # --------------------------------------------------------- admission

    def qos_admit(self, q) -> bool:
        """Block until the query is admitted by its lane — True — or
        it died / the coordinator is shutting down — False, and the
        caller must NOT execute (un-admitted queries stampeding into
        execution at shutdown would run unbounded; the legacy
        semaphore keeps them blocked). Enqueues FIFO within the
        query's group; dispatch picks the highest-priority lane first,
        weighted-fair within a lane. While waiting, a strictly-higher-
        priority entry periodically re-evaluates preemption —
        hysteresis-refused victims become eligible again when their
        grace expires."""
        group = self.group_of(q)
        entry = _QosEntry(q, group)
        victim = None
        with self._cond:
            entry.seq = next(self._seq)
            self._entries[q.qid] = entry
            group.queue.append(entry)
            self._dispatch_locked()
            if entry.state == "WAITING":
                victim = self._preempt_locked(entry)
        if victim is not None:
            REGISTRY.counter("qos.preempt_triggers").update()
            self._apply_suspend_effects(victim)
        while not entry.event.wait(timeout=0.1):
            if q.done.is_set() or self.coord._shutting_down:
                return False
            victim = None
            with self._cond:
                if entry.state == "WAITING":
                    victim = self._preempt_locked(entry)
            if victim is not None:
                REGISTRY.counter("qos.preempt_triggers").update()
                self._apply_suspend_effects(victim)
        REGISTRY.counter("qos.admitted").update()
        return True

    def qos_release(self, q) -> None:
        """Query finished (any terminal state): free its slot — or its
        lane entry, if it died while waiting/suspended — fold its
        latency into the group reservoir, and dispatch the next
        admission."""
        pending = self._entries.get(q.qid)
        if pending is not None and pending.resume_pending:
            # resumed but never parked (the suspension landed while no
            # stage thread was at a checkpoint): close the resume out
            # here so suspension/resume accounting stays paired
            self._finish_resume(pending)
        with self._cond:
            entry = self._entries.pop(q.qid, None)
            if entry is None:
                return
            if self._running.pop(q.qid, None) is not None:
                entry.group.running -= 1
            else:
                try:
                    entry.group.queue.remove(entry)
                except ValueError:
                    pass  # dispatched-but-skipped (died waiting)
            entry.resume_pending = False
            entry.group.queries += 1
            miss = False
            if q.state == "FINISHED":
                elapsed = q.stats.elapsed_ms
                entry.group.latency.add(elapsed)
                target = entry.group.target_p99_ms
                if target and elapsed > target:
                    entry.group.slo_misses += 1
                    miss = True
            self._dispatch_locked()
        if miss:
            REGISTRY.counter("qos.slo_misses").update()

    def _dispatch_locked(self) -> None:
        """Fill free slots: strict priority across lanes, weighted-fair
        (smallest running/weight, then name) among same-priority
        groups, FIFO within a group. Resume re-entries sit at their
        lane's front, so a suspended query resumes before its group's
        queued newcomers."""
        while len(self._running) < self.slots:
            best = None
            for g in self._groups.values():
                if not g.queue:
                    continue
                key = (-g.priority, g.running / g.weight, g.name)
                if best is None or key < best[0]:
                    best = (key, g)
            if best is None:
                return
            g = best[1]
            entry = g.queue.popleft()
            if entry.q.done.is_set():
                continue  # died while waiting: never occupy a slot
            entry.state = "RUNNING"
            if entry.resuming:
                entry.resuming = False
                entry.resume_pending = True
            self._running[entry.qid] = entry
            g.running += 1
            entry.event.set()

    # -------------------------------------------------------- preemption

    def _suspendable_locked(self, e: _QosEntry) -> bool:
        """Hysteresis gate: under the lifetime cap AND outside the
        post-resume grace window. An entry whose resume is dispatched
        but not yet finalized (no stage thread reached a checkpoint)
        is inside the grace by definition — re-suspending it would
        silently cancel the pending resume close-out and leave the
        suspend/resume accounting unpaired."""
        if e.resume_pending:
            return False
        if e.suspensions >= self.max_suspensions:
            return False
        if (
            e.last_resume
            and time.monotonic() - e.last_resume < self.resume_grace_s
        ):
            return False
        return True

    def _choose_victim_locked(
        self, waiter: _QosEntry
    ) -> Optional[_QosEntry]:
        """Victim among RUNNING entries of strictly lower priority:
        lowest priority first, then newest admission (the least sunk
        work — mirroring the memory killer's last-admitted policy),
        hysteresis-filtered."""
        best = None
        for e in self._running.values():
            if e.priority >= waiter.priority or e.q.done.is_set():
                continue
            if e.q.state != "RUNNING":
                # QUEUED = parked in the arbiter admission hold (no
                # compute to free — suspending it only burns its
                # lifetime cap and desyncs the state machine);
                # FINISHED/FAILED = closing out, nothing to suspend
                continue
            if not self._suspendable_locked(e):
                continue
            key = (e.priority, -e.seq)
            if best is None or key < best[0]:
                best = (key, e)
        return best[1] if best else None

    def _preempt_locked(
        self, waiter: _QosEntry
    ) -> Optional[_QosEntry]:
        victim = self._choose_victim_locked(waiter)
        if victim is None:
            return None
        self._suspend_locked(victim)
        self._dispatch_locked()
        return victim

    def _suspend_locked(self, e: _QosEntry) -> None:
        """Slot-accounting half of a suspension (the side effects —
        journal frame, memory release, query state — run outside the
        lock in ``_apply_suspend_effects``). The entry re-enqueues at
        its lane's FRONT for resume."""
        e.state = "SUSPENDED"
        e.suspend_t0 = time.monotonic()
        e.suspensions += 1
        e.resuming = True
        e.resume_pending = False
        e.effects_done.clear()
        e.event.clear()
        self._running.pop(e.qid, None)
        e.group.running -= 1
        e.group.suspensions += 1
        e.group.queue.appendleft(e)
        # the query-visible state flips HERE, under the lock: a resume
        # close-out (_finish_resume, same lock + effects_done barrier)
        # is strictly ordered after it, so an instant re-dispatch can
        # never leave a running query stuck SUSPENDED. Terminal states
        # keep priority — the victim may be closing out concurrently
        q = e.q
        if not q.done.is_set() and q.state not in (
            "FINISHED",
            "FAILED",
        ):
            q.state = "SUSPENDED"
            q.stats.state = "SUSPENDED"

    def _apply_suspend_effects(self, e: _QosEntry) -> None:
        """Side effects of one suspension decision, OUTSIDE the
        controller lock (journal appends and spool scans block):
        journal the frame with the victim's spooled progress and
        release its cluster memory reservation (the arbiter stops
        charging a parked query immediately; its draining worker
        tasks re-assert whatever they still hold on their next
        heartbeats). The query-visible SUSPENDED flip already
        happened under the lock in ``_suspend_locked``;
        ``effects_done`` (set in the finally) is the barrier a resume
        close-out orders itself after — an instant re-dispatch can
        never journal ``qos_resume`` before ``qos_suspend``."""
        q = e.q
        try:
            q.qos_suspensions = e.suspensions
            REGISTRY.counter("qos.suspensions").update()
            spooled = 0
            spool = getattr(self.coord, "spool", None)
            if spool is not None:
                try:
                    spooled = spool.committed_for_query(q.qid)
                except Exception:
                    pass
            with q._stats_lock:
                stages = sum(
                    1 for st in q.stats.stages if st.state == "RUNNING"
                )
            journal = getattr(self.coord, "journal", None)
            if journal is not None:
                journal.record_suspend(
                    q.qid,
                    spooled_attempts=spooled,
                    running_stages=stages,
                    suspensions=e.suspensions,
                )
            log.info(
                "qos: suspended %s (group %s, suspension %d, %d spooled "
                "attempt(s), %d running stage(s))",
                q.qid, e.group.name, e.suspensions, spooled, stages,
            )
            try:
                # cluster reservation: drop the victim from the
                # arbiter's cached reports now, and surrender the
                # coordinator pool's own accounting (the parked query
                # re-reserves on resume; later paired releases clamp
                # at zero — the memory-kill re-admission lane's
                # discipline)
                self.coord.arbiter.suspend_release(q.qid)
                self.coord.memory_pool.release(q.qid)
            except Exception:
                log.warning(
                    "qos: suspend memory release failed for %s",
                    q.qid, exc_info=True,
                )
        finally:
            e.effects_done.set()

    # ------------------------------------------------------- checkpoints

    def qos_checkpoint(self, q) -> None:
        """Cooperative suspension point, called by the coordinator's
        stage machinery between ranges: a suspended query's stage
        threads PARK here until resume (claimed ranges already ran to
        completion — tasks exit clean), then the first thread to wake
        finalizes the resume. Also the ``suspend_storm`` fault hook:
        an armed rule triggers a preemption against this query even
        with no higher-priority waiter, which is how the re-suspend
        hysteresis is tested."""
        if q is None:
            return
        if faults.maybe_inject_qos(q.qid):
            self._storm_trigger(q)
        entry = self._entries.get(q.qid)
        if entry is None:
            return
        if not entry.event.is_set():
            while not entry.event.wait(timeout=0.1):
                if q.done.is_set() or self.coord._shutting_down:
                    return
        if entry.resume_pending:
            self._finish_resume(entry)

    def _storm_trigger(self, q) -> None:
        """One injected preemption trigger against ``q`` (the
        ``suspend_storm`` fault rule): counts as a trigger whether or
        not hysteresis lets it suspend."""
        REGISTRY.counter("qos.preempt_triggers").update()
        victim = None
        with self._cond:
            e = self._running.get(q.qid)
            if (
                e is not None
                and e.q.state == "RUNNING"
                and self._suspendable_locked(e)
            ):
                self._suspend_locked(e)
                self._dispatch_locked()
                victim = e
        if victim is not None:
            self._apply_suspend_effects(victim)

    def _finish_resume(self, entry: _QosEntry) -> None:
        """Exactly-once resume close-out (the winning parked thread, or
        the release path for a query that never parked again). Ordered
        AFTER the matching suspension's side effects: an instant
        re-dispatch (storm with a free slot) must not journal the
        resume before the suspend frame or un-suspend a state write in
        flight."""
        entry.effects_done.wait(timeout=10.0)
        dur = 0.0
        fire = False
        with self._cond:
            if entry.resume_pending:
                entry.resume_pending = False
                now = time.monotonic()
                dur = (now - entry.suspend_t0) * 1000.0
                entry.last_resume = now
                entry.suspended_ms += dur
                entry.group.resumes += 1
                fire = True
        if not fire:
            return
        q = entry.q
        if not q.done.is_set() and q.state == "SUSPENDED":
            # flip only a still-SUSPENDED query: a terminal state
            # written concurrently (kill, failure) keeps priority
            q.state = "RUNNING"
            q.stats.state = "RUNNING"
        q.qos_resumes = getattr(q, "qos_resumes", 0) + 1
        q.qos_suspended_ms = (
            getattr(q, "qos_suspended_ms", 0.0) + dur
        )
        REGISTRY.counter("qos.resumes").update()
        REGISTRY.distribution("qos.suspended_ms").add(dur)
        journal = getattr(self.coord, "journal", None)
        if journal is not None:
            journal.record_resume(q.qid, suspended_ms=dur)
        log.info(
            "qos: resumed %s after %.0fms suspended", q.qid, dur
        )

    # ------------------------------------------------------- speculation

    def speculation_scale(self, q) -> float:
        """Deadline-aware straggler speculation: multiply the PR 2
        threshold by this factor. 1.0 with no SLO; shrinks linearly to
        ``SPECULATION_FLOOR`` as elapsed time eats the group's
        ``target-p99-ms`` budget — a query about to miss its SLO
        speculates earlier."""
        target = self.group_of(q).target_p99_ms
        if not target or target <= 0:
            return 1.0
        frac = q.stats.elapsed_ms / target
        return min(1.0, max(SPECULATION_FLOOR, 1.0 - frac))

    # ----------------------------------------------------- observability

    def query_info(self, q) -> dict:
        """The QueryInfo ``qos`` section for one query."""
        g = self.group_of(q)
        return {
            "group": g.name,
            "priority": g.priority,
            "target_p99_ms": g.target_p99_ms,
            "suspensions": getattr(q, "qos_suspensions", 0),
            "resumes": getattr(q, "qos_resumes", 0),
            "suspended_ms": getattr(q, "qos_suspended_ms", 0.0),
        }

    def background_idle(self) -> bool:
        """May low-priority background work (lakehouse compaction,
        server/ingest.py) run now? True when no query is running or
        queued in any lane — background rewrites yield to ANY live
        foreground work rather than competing for device time."""
        with self._cond:
            return not self._running and all(
                not g.queue for g in self._groups.values()
            )

    def lane_occupancy(self) -> dict:
        """Per-lane live occupancy — the QoS share of this
        coordinator's lease payload (server/lease.py): peers fold it
        into their ``system.runtime.qos`` view so lane pressure is
        visible cluster-wide across N admitters."""
        with self._cond:
            return {
                g.name: {
                    "running": g.running,
                    "queued": len(g.queue),
                }
                for g in self._groups.values()
            }

    def view_rows(self) -> List[dict]:
        """``system.runtime.qos``: one row per lane member. With the
        multi-coordinator lease plane on (``peer_lanes_fn`` set by the
        coordinator), live peers' published lane occupancy folds into
        the running/queued columns — the view reads cluster-wide;
        single-coordinator deploys are bit-exact."""
        peer_lanes: dict = {}
        if self.peer_lanes_fn is not None:
            try:
                peer_lanes = self.peer_lanes_fn() or {}
            except Exception:
                peer_lanes = {}
        with self._cond:
            snap = []
            for g in self._groups.values():
                suspended = sum(
                    1 for e in g.queue if e.state == "SUSPENDED"
                )
                # suspended entries park at the lane front — they are
                # not "queued" occupancy, so the two columns stay
                # disjoint (running + queued + suspended = live)
                snap.append(
                    (
                        g,
                        g.running,
                        len(g.queue) - suspended,
                        suspended,
                    )
                )
        rows = []
        for g, running, queued, suspended in sorted(
            snap, key=lambda t: (-t[0].priority, t[0].name)
        ):
            for lanes in peer_lanes.values():
                peer = lanes.get(g.name)
                if isinstance(peer, dict):
                    running += int(peer.get("running", 0))
                    queued += int(peer.get("queued", 0))
            v = g.latency.values()
            rows.append(
                {
                    "group": g.name,
                    "priority": g.priority,
                    "target_p99_ms": g.target_p99_ms or 0.0,
                    "queries": g.queries,
                    "running": running,
                    "queued": queued,
                    "suspended": suspended,
                    "p50_ms": v["p50"],
                    "p99_ms": v["p99"],
                    "slo_misses": g.slo_misses,
                    "suspensions": g.suspensions,
                    "resumes": g.resumes,
                }
            )
        return rows
