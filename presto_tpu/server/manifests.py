"""Durable lakehouse snapshots: crash-safe manifest commits over
immutable parquet data files.

Reference parity: the open-table-format direction of the survey
(SURVEY.md §2.2 connector long-tail; PAPER.md's Iceberg/Hudi
ecosystem argument) — a table is a chain of immutable snapshots, each
described by a manifest listing the data files that make it up, with
enough per-file metadata (row counts, column min/max) for planners to
prune files without opening them. PR 12's snapshot SPI gave the
engine pin-once-per-scan version handles; this module gives those
handles something durable to point at.

On-disk shape (one directory per table under ``lakehouse.path``)::

    <root>/<catalog>.<schema>.<table>/
        data/<sid>-<nonce>.parquet      immutable row chunks
        manifests/<sid>.manifest        one crc32-framed JSON line
        _current                        pointer to the tip snapshot

A manifest is ONE checksummed frame (the journal/spool/ingest WAL
idiom: ``{crc32:08x} {payload}``) holding the snapshot id, the parent
snapshot id, the table schema, and the FULL cumulative file list —
reads are O(1) manifest loads and rollback needs no log replay.

Commit protocol (the crash-safety contract, chaos-tested in
``tests/test_lakehouse.py``): data files are written to a temp name,
fsynced, and atomically renamed FIRST; the manifest is written to a
temp name, fsynced, and atomically renamed SECOND; the ``_current``
pointer is swapped (temp + fsync + atomic rename) LAST. A kill or an
injected ``io_error`` at ANY point leaves either the old tip or the
new tip — never a half-commit. Failed attempts leave only orphan
files (never a reachable manifest), cleaned by the TTL'd GC.

Torn or corrupt manifests are detected by checksum at read time and
rolled back: a tip whose manifest fails validation falls back to the
newest older valid manifest and the pointer is repaired in place
(``lakehouse.rollbacks`` counts it).

Frame construction/parsing, data-file publication, and the
``_current`` pointer swap are confined to this module
(``tools/analyze.py`` ``manifest-plane`` rule) — a second pointer
writer or an ad-hoc manifest parser elsewhere would silently break
the atomic-commit and rollback guarantees.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import uuid
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu import types as T
from presto_tpu.connectors._arrow import (
    arrow_column_to_payload as _arrow_column_to_payload,
)
from presto_tpu.connectors.spi import (
    ColumnStats,
    ConnectorSplit,
    SplitSource,
    TableHandle,
    TableStats,
    coalesce_kept_chunks,
)
from presto_tpu.utils import faults
from presto_tpu.utils.metrics import REGISTRY

log = logging.getLogger("presto_tpu.lakehouse")

_CURRENT = "_current"
_MANIFEST_DIR = "manifests"
_DATA_DIR = "data"
_MANIFEST_SUFFIX = ".manifest"
_TMP_SUFFIX = ".tmp"

#: default split size over manifest-backed tables (rows per split)
DEFAULT_TARGET_FILE_BYTES = 64 * 1024 * 1024


class ManifestError(RuntimeError):
    pass


# ----------------------------------------------------------------- frames


def _manifest_frame(payload: str) -> str:
    """One checksummed manifest frame — the same crc32-prefixed idiom
    as the journal/spool/ingest WAL, so a torn write is detected by
    the same check."""
    return f"{zlib.crc32(payload.encode()) & 0xFFFFFFFF:08x} {payload}"


def _parse_manifest_line(line: str) -> Optional[dict]:
    """Frame -> record dict, or None for torn/corrupt content."""
    line = line.strip()
    if not line:
        return None
    crc_hex, sep, payload = line.partition(" ")
    if not sep or len(crc_hex) != 8:
        return None
    try:
        crc = int(crc_hex, 16)
    except ValueError:
        return None
    if zlib.crc32(payload.encode()) & 0xFFFFFFFF != crc:
        return None
    try:
        rec = json.loads(payload)
    except Exception:
        return None
    return rec if isinstance(rec, dict) else None


# ------------------------------------------------------------- model


@dataclass(frozen=True)
class DataFile:
    """One immutable parquet chunk of a snapshot."""

    name: str
    rows: int
    bytes: int
    #: per-column [lo, hi] for plain numeric columns (pruning input;
    #: missing stats over-retain, mirroring footer-stats discipline)
    minmax: Tuple[Tuple[str, Tuple[float, float]], ...] = ()

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "rows": self.rows,
            "bytes": self.bytes,
            "minmax": {c: list(mm) for c, mm in self.minmax},
        }

    @staticmethod
    def from_json(rec: dict) -> "DataFile":
        return DataFile(
            name=str(rec["name"]),
            rows=int(rec["rows"]),
            bytes=int(rec.get("bytes", 0)),
            minmax=tuple(
                sorted(
                    (str(c), (mm[0], mm[1]))
                    for c, mm in (rec.get("minmax") or {}).items()
                )
            ),
        )


@dataclass(frozen=True)
class Manifest:
    """One committed snapshot: schema + the full file list."""

    snapshot: int
    parent: Optional[int]
    table: str  #: dotted catalog.schema.table
    schema: Tuple[Tuple[str, str], ...]  #: (col, engine type text)
    files: Tuple[DataFile, ...]
    row_count: int
    compaction: bool = False
    ts: float = 0.0

    def engine_schema(self) -> Dict[str, T.DataType]:
        return {c: T.parse_type(t) for c, t in self.schema}

    def to_json(self) -> dict:
        return {
            "snapshot": self.snapshot,
            "parent": self.parent,
            "table": self.table,
            "schema": {c: t for c, t in self.schema},
            "files": [f.to_json() for f in self.files],
            "row_count": self.row_count,
            "compaction": self.compaction,
            "ts": self.ts,
        }

    @staticmethod
    def from_json(rec: dict) -> "Manifest":
        return Manifest(
            snapshot=int(rec["snapshot"]),
            parent=(
                int(rec["parent"]) if rec.get("parent") is not None
                else None
            ),
            table=str(rec.get("table", "")),
            schema=tuple(
                (str(c), str(t))
                for c, t in (rec.get("schema") or {}).items()
            ),
            files=tuple(
                DataFile.from_json(f) for f in rec.get("files") or ()
            ),
            row_count=int(rec.get("row_count", 0)),
            compaction=bool(rec.get("compaction", False)),
            ts=float(rec.get("ts", 0.0)),
        )


# ----------------------------------------------------- durable writes


def _fsync_path(path: str) -> None:
    faults.maybe_inject_io("fsync", path)
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    # directory fsync makes the rename itself durable; tolerate
    # platforms/filesystems that refuse O_RDONLY on directories
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _publish_file(tmp: str, final: str) -> None:
    """fsync the temp file, atomically rename it into place, fsync
    the directory — the durable-publication step all three commit
    stages share."""
    _fsync_path(tmp)
    faults.maybe_inject_io("rename", final)
    os.replace(tmp, final)
    _fsync_dir(os.path.dirname(final))


# ------------------------------------------------------ arrow bridge


def _engine_to_arrow(t: T.DataType):
    import pyarrow as pa

    if getattr(t, "is_decimal", False):
        return pa.decimal128(t.precision, t.scale)
    name = t.name
    if name == "boolean":
        return pa.bool_()
    if name == "bigint":
        return pa.int64()
    if name in ("integer", "smallint", "tinyint"):
        return pa.int32()
    if name == "double":
        return pa.float64()
    if name == "real":
        return pa.float32()
    if name == "date":
        return pa.date32()
    if name == "timestamp":
        return pa.timestamp("us")
    return pa.string()


def _delta_to_arrow(schema: Dict[str, T.DataType], delta: Dict[str, Sequence]):
    import pyarrow as pa

    arrays, fields = [], []
    for c, t in schema.items():
        at = _engine_to_arrow(t)
        arrays.append(pa.array(list(delta.get(c, ())), type=at))
        fields.append(pa.field(c, at))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def _table_minmax(tbl) -> Tuple[Tuple[str, Tuple[float, float]], ...]:
    """Per-column [lo, hi] for plain int/float columns of one arrow
    chunk — the pruning stats recorded in the manifest."""
    import pyarrow as pa
    import pyarrow.compute as pc

    out = []
    for field in tbl.schema:
        if not (
            pa.types.is_integer(field.type)
            or pa.types.is_floating(field.type)
        ):
            continue
        col = tbl.column(field.name)
        if col.null_count == len(col):
            continue
        mm = pc.min_max(col)
        lo, hi = mm["min"].as_py(), mm["max"].as_py()
        if lo is None or hi is None:
            continue
        out.append((field.name, (lo, hi)))
    return tuple(sorted(out))


class _FileStatsShim:
    """Adapts a manifest ``minmax`` entry to the pyarrow statistics
    surface ``parquet.rowgroup_matches`` consumes."""

    __slots__ = ("has_min_max", "min", "max")

    def __init__(self, lo, hi):
        self.has_min_max = True
        self.min = lo
        self.max = hi


# ------------------------------------------------------------- store


class ManifestStore:
    """One lakehouse root: durable snapshot commits, validated reads
    with rollback-to-parent, compaction, and TTL'd orphan GC.

    The store is stateless over the directory apart from an immutable
    manifest parse cache — ingest and the file connectors can hold
    independent instances over the same root and stay coherent
    (every tip read goes through the ``_current`` pointer)."""

    def __init__(
        self,
        root: str,
        target_file_bytes: int = DEFAULT_TARGET_FILE_BYTES,
    ):
        self.root = root
        self.target_file_bytes = max(int(target_file_bytes), 1)
        os.makedirs(root, exist_ok=True)
        self._mu = threading.Lock()  # guards the parse cache only
        self._cache: Dict[Tuple[str, int], Manifest] = {}

    # ------------------------------------------------------- layout

    def _tdir(self, tk: Tuple[str, str, str]) -> str:
        return os.path.join(self.root, ".".join(tk))

    def tables(self) -> List[Tuple[str, str, str]]:
        out = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return out
        for name in names:
            parts = tuple(name.split("."))
            if len(parts) != 3:
                continue
            if os.path.exists(os.path.join(self.root, name, _CURRENT)):
                out.append(parts)  # type: ignore[arg-type]
        return out

    def has_table(self, tk: Tuple[str, str, str]) -> bool:
        return os.path.exists(os.path.join(self._tdir(tk), _CURRENT))

    # -------------------------------------------------------- reads

    def _read_current(self, tdir: str) -> Optional[int]:
        try:
            with open(os.path.join(tdir, _CURRENT), encoding="utf-8") as f:
                rec = _parse_manifest_line(f.readline())
        except OSError:
            return None
        if rec is None or "sid" not in rec:
            return None
        try:
            return int(rec["sid"])
        except (TypeError, ValueError):
            return None

    def _load(self, tk, sid: int) -> Optional[Manifest]:
        """Checksum-validated read of one manifest file (no chain
        membership check — callers validate reachability)."""
        key = (".".join(tk), sid)
        with self._mu:
            m = self._cache.get(key)
        if m is not None:
            return m
        path = os.path.join(
            self._tdir(tk), _MANIFEST_DIR, f"{sid}{_MANIFEST_SUFFIX}"
        )
        try:
            with open(path, encoding="utf-8") as f:
                rec = _parse_manifest_line(f.readline())
        except OSError:
            return None
        if rec is None:
            return None
        try:
            m = Manifest.from_json(rec)
        except Exception:
            return None
        if m.snapshot != sid:
            return None
        with self._mu:
            self._cache[key] = m
        return m

    def _manifest_sids_on_disk(self, tk) -> List[int]:
        mdir = os.path.join(self._tdir(tk), _MANIFEST_DIR)
        out = []
        try:
            names = os.listdir(mdir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(_MANIFEST_SUFFIX):
                continue
            try:
                out.append(int(name[: -len(_MANIFEST_SUFFIX)]))
            except ValueError:
                continue
        return sorted(out)

    def manifest(
        self, tk: Tuple[str, str, str], sid: Optional[int] = None
    ) -> Optional[Manifest]:
        """The tip manifest (``sid=None``) or a historic snapshot in
        the tip's parent chain. A torn/corrupt tip rolls back to the
        newest older VALID manifest and repairs the pointer; a ``sid``
        outside the chain (never committed, or expired past the GC
        TTL) returns None."""
        tdir = self._tdir(tk)
        tip_sid = self._read_current(tdir)
        tip = None
        if tip_sid is not None:
            tip = self._load(tk, tip_sid)
        if tip is None:
            # pointer missing/corrupt, or its target torn: fall back
            # to the newest valid manifest on disk below the pointer
            # (== the parent — failed commits never reach the swap,
            # so any NEWER manifest file is unreachable by design)
            candidates = [
                s
                for s in reversed(self._manifest_sids_on_disk(tk))
                if tip_sid is None or s < tip_sid
            ]
            for s in candidates:
                tip = self._load(tk, s)
                if tip is not None:
                    REGISTRY.counter("lakehouse.rollbacks").update()
                    log.warning(
                        "lakehouse %s: tip %r unreadable — rolled "
                        "back to snapshot %d",
                        ".".join(tk), tip_sid, s,
                    )
                    try:
                        self._swap_current(tdir, s)
                    except OSError:
                        pass  # serve the parent even if repair fails
                    break
            if tip is None:
                return None
        if sid is None or sid == tip.snapshot:
            return tip
        # historic read: walk the parent chain — orphan manifests of
        # failed commits are NOT reachable and never served
        m = tip
        while m is not None and m.parent is not None:
            m = self._load(tk, m.parent)
            if m is not None and m.snapshot == sid:
                return m
        return None

    def current_sid(self, tk) -> Optional[int]:
        m = self.manifest(tk)
        return m.snapshot if m is not None else None

    def sids(self, tk) -> List[int]:
        """Live snapshot ids, ascending (the tip's parent chain)."""
        out = []
        m = self.manifest(tk)
        while m is not None:
            out.append(m.snapshot)
            if m.parent is None:
                break
            m = self._load(tk, m.parent)
        return sorted(out)

    def schema(self, tk) -> Optional[Dict[str, T.DataType]]:
        m = self.manifest(tk)
        return m.engine_schema() if m is not None else None

    # ------------------------------------------------------- commit

    def _swap_current(self, tdir: str, sid: int) -> None:
        """The LAST step of a commit: atomically repoint the table at
        its new tip. This rename is the durability point."""
        final = os.path.join(tdir, _CURRENT)
        tmp = final + _TMP_SUFFIX
        faults.maybe_inject_io("write", final)
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(_manifest_frame(json.dumps({"sid": sid})) + "\n")
            f.flush()
        _publish_file(tmp, final)

    def _write_manifest(self, tk, m: Manifest) -> None:
        mdir = os.path.join(self._tdir(tk), _MANIFEST_DIR)
        os.makedirs(mdir, exist_ok=True)
        final = os.path.join(mdir, f"{m.snapshot}{_MANIFEST_SUFFIX}")
        tmp = final + _TMP_SUFFIX
        faults.maybe_inject_io("write", final)
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(
                _manifest_frame(json.dumps(m.to_json(), default=str))
                + "\n"
            )
            f.flush()
        _publish_file(tmp, final)
        # a retried commit may overwrite an orphan manifest of a
        # failed attempt at the same sid — drop any stale parse
        with self._mu:
            self._cache.pop((".".join(tk), m.snapshot), None)

    def _write_data_file(self, tk, sid: int, tbl) -> DataFile:
        """Publish one immutable parquet chunk: temp write, fsync,
        atomic rename. The nonce keeps retried commits from colliding
        with the orphan of a failed attempt."""
        import pyarrow.parquet as pq

        ddir = os.path.join(self._tdir(tk), _DATA_DIR)
        os.makedirs(ddir, exist_ok=True)
        name = f"{sid:012d}-{uuid.uuid4().hex[:8]}.parquet"
        final = os.path.join(ddir, name)
        tmp = final + _TMP_SUFFIX
        faults.maybe_inject_io("write", final)
        pq.write_table(tbl, tmp)
        _publish_file(tmp, final)
        REGISTRY.counter("lakehouse.files_written").update()
        nbytes = os.path.getsize(final)
        REGISTRY.counter("lakehouse.bytes_written").update(nbytes)
        return DataFile(
            name=name,
            rows=tbl.num_rows,
            bytes=nbytes,
            minmax=_table_minmax(tbl),
        )

    def _chunk_rows(self, tbl) -> List:
        """Split one arrow table into ~target-file-bytes chunks,
        preserving row order."""
        if tbl.num_rows == 0:
            return []
        nbytes = max(tbl.nbytes, 1)
        nchunks = max(1, -(-nbytes // self.target_file_bytes))
        if nchunks == 1:
            return [tbl]
        per = -(-tbl.num_rows // nchunks)
        return [
            tbl.slice(i, per) for i in range(0, tbl.num_rows, per)
        ]

    def _publish(
        self,
        tk: Tuple[str, str, str],
        schema: Dict[str, T.DataType],
        tbl,
        sid: int,
        *,
        keep_parent_files: bool,
        compaction: bool = False,
    ) -> Manifest:
        """The three-stage crash-safe commit: data files, manifest,
        pointer — in that order, each durably published before the
        next begins."""
        parent = self.manifest(tk)
        if parent is not None and sid <= parent.snapshot and not compaction:
            raise ManifestError(
                f"snapshot id {sid} not beyond tip {parent.snapshot} "
                f"for {'.'.join(tk)}"
            )
        new_files: List[DataFile] = []
        if tbl is not None:
            for chunk in self._chunk_rows(tbl):
                new_files.append(self._write_data_file(tk, sid, chunk))
        files: Tuple[DataFile, ...] = tuple(new_files)
        if keep_parent_files and parent is not None:
            files = parent.files + files
        m = Manifest(
            snapshot=sid,
            parent=parent.snapshot if parent is not None else None,
            table=".".join(tk),
            schema=tuple((c, str(t)) for c, t in schema.items()),
            files=files,
            row_count=sum(f.rows for f in files),
            compaction=compaction,
            ts=time.time(),
        )
        self._write_manifest(tk, m)
        self._swap_current(self._tdir(tk), sid)
        with self._mu:
            self._cache[(".".join(tk), sid)] = m
        REGISTRY.counter("lakehouse.commits").update()
        return m

    def create_table(
        self, tk: Tuple[str, str, str], schema: Dict[str, T.DataType]
    ) -> Manifest:
        """Register an empty table as snapshot 0 (schema only)."""
        existing = self.manifest(tk)
        if existing is not None:
            raise ManifestError(f"table {'.'.join(tk)} already exists")
        os.makedirs(self._tdir(tk), exist_ok=True)
        return self._publish(
            tk, schema, None, 0, keep_parent_files=False
        )

    def commit(
        self,
        tk: Tuple[str, str, str],
        schema: Dict[str, T.DataType],
        delta: Dict[str, Sequence],
        sid: int,
    ) -> Manifest:
        """Durably append one committed delta as snapshot ``sid``.
        Raises (cleanly, leaving the old tip reachable) on any I/O
        failure — the caller retries the whole commit."""
        tbl = _delta_to_arrow(schema, delta)
        return self._publish(
            tk, schema, tbl, sid, keep_parent_files=True
        )

    # -------------------------------------------------------- serve

    def read_arrow(self, tk, m: Manifest, columns=None):
        """The snapshot's rows as one arrow table, in manifest file
        order (row order is part of the snapshot contract)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        ddir = os.path.join(self._tdir(tk), _DATA_DIR)
        parts = []
        for f in m.files:
            parts.append(
                pq.read_table(
                    os.path.join(ddir, f.name), columns=columns
                )
            )
        if not parts:
            schema = m.engine_schema()
            names = columns if columns is not None else list(schema)
            return pa.Table.from_arrays(
                [
                    pa.array([], type=_engine_to_arrow(schema[c]))
                    for c in names
                ],
                names=list(names),
            )
        return pa.concat_tables(parts)

    def read_values(self, tk, sid: Optional[int] = None) -> Optional[
        Dict[str, list]
    ]:
        """The snapshot's rows as python values (restore path: feeds
        ``commit_snapshot`` on the volatile store bit-identically to
        the original appends)."""
        m = self.manifest(tk, sid)
        if m is None:
            return None
        tbl = self.read_arrow(tk, m)
        return {
            name: tbl.column(name).to_pylist()
            for name in tbl.schema.names
        }

    def splits_for_manifest(
        self,
        m: Manifest,
        handle: TableHandle,
        target_rows: int,
        constraint=(),
    ) -> List[ConnectorSplit]:
        """File-level pruning: each data file is a chunk, kept when
        its manifest min/max may satisfy the constraint (missing
        stats over-retain); kept runs coalesce into row-range splits
        in the snapshot's global row space — the same loop the file
        connectors use for row groups/stripes, one level up."""
        from presto_tpu.connectors.parquet import rowgroup_matches

        chunk_rows: List[int] = []
        keep: List[bool] = []
        for f in m.files:
            kept = True
            if constraint:
                mm = dict(f.minmax)
                for col, domain in constraint:
                    ent = mm.get(col)
                    shim = (
                        _FileStatsShim(ent[0], ent[1])
                        if ent is not None
                        else None
                    )
                    if not rowgroup_matches(shim, domain):
                        kept = False
                        break
            chunk_rows.append(f.rows)
            keep.append(kept)
        return coalesce_kept_chunks(
            handle, chunk_rows, keep, target_rows
        )

    def page_payloads(
        self,
        tk,
        m: Manifest,
        columns: Dict[str, T.DataType],
        row_start: int,
        row_end: int,
    ) -> Tuple[int, Dict[str, object]]:
        """Engine staging payloads for one row range of a snapshot —
        the split's global rows mapped onto the files that hold them,
        converted through the shared arrow bridge."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        ddir = os.path.join(self._tdir(tk), _DATA_DIR)
        names = list(columns)
        parts = []
        start = 0
        for f in m.files:
            end = start + f.rows
            lo = max(row_start, start)
            hi = min(row_end, end)
            if lo < hi:
                tbl = pq.read_table(
                    os.path.join(ddir, f.name), columns=names
                )
                parts.append(tbl.slice(lo - start, hi - lo))
            start = end
        if not parts:
            return 0, {
                c: _arrow_column_to_payload(
                    pa.chunked_array(
                        [pa.array([], type=_engine_to_arrow(t))]
                    ),
                    t,
                )
                for c, t in columns.items()
            }
        merged = pa.concat_tables(parts)
        payloads = {
            c: _arrow_column_to_payload(merged.column(c), columns[c])
            for c in names
        }
        return merged.num_rows, payloads

    # --------------------------------------------------- compaction

    def compact(
        self,
        tk: Tuple[str, str, str],
        new_sid: int,
        *,
        min_files: int = 4,
    ) -> Optional[Manifest]:
        """Rewrite the tip's small files into ~target-file-bytes
        chunks and publish the result as a NEW snapshot (same rows,
        same order). Pinned readers keep serving the old files —
        nothing is deleted here; the TTL'd GC reclaims them once the
        old snapshots expire."""
        m = self.manifest(tk)
        if m is None or len(m.files) < max(min_files, 2):
            return None
        small = sum(
            1 for f in m.files if f.bytes < self.target_file_bytes
        )
        if small < max(min_files, 2):
            return None
        tbl = self.read_arrow(tk, m)
        out = self._publish(
            tk,
            m.engine_schema(),
            tbl,
            new_sid,
            keep_parent_files=False,
            compaction=True,
        )
        REGISTRY.counter("lakehouse.compactions").update()
        return out

    # ----------------------------------------------------------- gc

    def gc_orphans(self, ttl_s: float) -> int:
        """Reclaim (a) manifests no longer reachable from any tip —
        failed commits and compacted-away history — and (b) data
        files referenced by no remaining manifest, both only once
        older than ``ttl_s`` (pinned readers of recent snapshots keep
        their files). Returns the number of paths removed."""
        removed = 0
        cutoff = time.time() - max(float(ttl_s), 0.0)
        for tk in self.tables():
            tdir = self._tdir(tk)
            live = set(self.sids(tk))
            mdir = os.path.join(tdir, _MANIFEST_DIR)
            tip = self.current_sid(tk)
            for s in self._manifest_sids_on_disk(tk):
                if s in live and (s == tip or s > (tip or 0)):
                    continue
                path = os.path.join(mdir, f"{s}{_MANIFEST_SUFFIX}")
                # expire failed-commit orphans AND old history past
                # the TTL; expiry truncates time travel from the
                # oldest end (the chain walk stops at the gap)
                if s == tip:
                    continue
                try:
                    if os.path.getmtime(path) >= cutoff:
                        continue
                    os.remove(path)
                    removed += 1
                    with self._mu:
                        self._cache.pop((".".join(tk), s), None)
                except OSError:
                    continue
            # data files referenced by NO remaining valid manifest
            referenced = set()
            for s in self._manifest_sids_on_disk(tk):
                m = self._load(tk, s)
                if m is not None:
                    referenced.update(f.name for f in m.files)
            ddir = os.path.join(tdir, _DATA_DIR)
            try:
                names = os.listdir(ddir)
            except OSError:
                continue
            for name in names:
                if name in referenced:
                    continue
                path = os.path.join(ddir, name)
                try:
                    if os.path.getmtime(path) >= cutoff:
                        continue
                    os.remove(path)
                    removed += 1
                except OSError:
                    continue
        if removed:
            REGISTRY.counter("lakehouse.orphans_gcd").update(removed)
        return removed

    # -------------------------------------------------------- stats

    def table_stats(self, tk) -> Optional[dict]:
        """Per-table lakehouse state for ``system.runtime.snapshots``."""
        m = self.manifest(tk)
        if m is None:
            return None
        small = sum(
            1 for f in m.files if f.bytes < self.target_file_bytes
        )
        if m.compaction:
            state = "compacted"
        elif small >= 2:
            state = "pending"
        else:
            state = "none"
        return {
            "table": ".".join(tk),
            "snapshot_id": m.snapshot,
            "snapshots": len(self.sids(tk)),
            "files": len(m.files),
            "bytes": sum(f.bytes for f in m.files),
            "rows": m.row_count,
            "compaction": state,
        }


# --------------------------------------------------- connector mixin


class LakehouseConnectorMixin:
    """The manifest-backed table surface the file connectors share:
    pin, serve, commit, and list tables whose storage is a manifest
    chain (connector config ``lakehouse=<root>``). Lives HERE so the
    manifest internals stay confined to the manifest plane — the
    parquet/ORC connectors only COMPOSE these entry points with their
    legacy single-file paths (legacy tables stay unversioned and
    bit-exact)."""

    manifest_store: Optional[ManifestStore] = None
    _lake_catalog: Optional[str] = None

    def _init_lakehouse(
        self,
        lakehouse: Optional[str],
        catalog: Optional[str] = None,
        target_file_bytes: Optional[int] = None,
    ) -> None:
        self.manifest_store = None
        self._lake_catalog = catalog
        if lakehouse:
            self.manifest_store = ManifestStore(
                lakehouse,
                target_file_bytes=int(
                    target_file_bytes or DEFAULT_TARGET_FILE_BYTES
                ),
            )

    def _lake_owns(self, tk) -> bool:
        # without a ``catalog`` config the store root is assumed
        # single-catalog (listing is fuzzy; handle-keyed ops are exact)
        return self._lake_catalog is None or tk[0] == self._lake_catalog

    def lake_manifest(self, handle: TableHandle) -> Optional[Manifest]:
        """The manifest a handle reads (its pinned snapshot, or the
        tip), or None for legacy non-manifest tables. An explicitly
        pinned snapshot that is not in the live chain raises — time
        travel must never silently serve other rows."""
        store = self.manifest_store
        if store is None or not store.has_table(handle.table_key):
            return None
        m = store.manifest(handle.table_key, handle.snapshot)
        if m is None and handle.snapshot is not None:
            raise KeyError(
                f"snapshot {handle.snapshot} is not available for "
                f"{'.'.join(handle.table_key)}"
            )
        return m

    def pin_snapshot(self, handle: TableHandle) -> TableHandle:
        store = self.manifest_store
        if store is None or not store.has_table(handle.table_key):
            return handle
        if handle.snapshot is not None:
            if (
                store.manifest(handle.table_key, handle.snapshot)
                is None
            ):
                raise KeyError(
                    f"snapshot {handle.snapshot} is not available "
                    f"for {'.'.join(handle.table_key)}"
                )
            return handle
        sid = store.current_sid(handle.table_key)
        if sid is None:
            return handle
        return dataclasses.replace(handle, snapshot=sid)

    def current_snapshot_id(
        self, handle: TableHandle
    ) -> Optional[int]:
        store = self.manifest_store
        if store is None or not store.has_table(handle.table_key):
            return None
        return store.current_sid(handle.table_key)

    def commit_snapshot(
        self, handle: TableHandle, delta: Dict[str, Sequence], sid: int
    ) -> int:
        """The ingest lane's durable fold: publish the delta as a new
        manifest snapshot. Data IS visibility here — there is no
        separate volatile copy to fold."""
        store = self.manifest_store
        if store is None:
            raise ManifestError(
                "catalog has no lakehouse root (pass lakehouse=<dir>)"
            )
        schema = store.schema(handle.table_key)
        if schema is None:
            raise ManifestError(
                f"unknown lakehouse table {'.'.join(handle.table_key)}"
            )
        m = store.commit(handle.table_key, schema, delta, sid)
        return m.row_count

    def restore_snapshots(self, handle: TableHandle, pairs) -> None:
        """Restart no-op: the manifest chain IS the durable history."""

    def create_table(
        self, handle: TableHandle, schema: Dict[str, T.DataType]
    ) -> None:
        store = self.manifest_store
        if store is None:
            return super().create_table(handle, schema)
        store.create_table(handle.table_key, schema)

    def lake_splits(
        self,
        handle: TableHandle,
        target_split_rows: int,
        constraint=(),
    ) -> Optional[SplitSource]:
        m = self.lake_manifest(handle)
        if m is None:
            return None
        return SplitSource(
            self.manifest_store.splits_for_manifest(
                m, handle, target_split_rows, constraint
            )
        )

    def lake_page_source(
        self, split: ConnectorSplit, columns: Sequence[str]
    ) -> Optional[Dict[str, object]]:
        m = self.lake_manifest(split.table)
        if m is None:
            return None
        schema = m.engine_schema()
        _n, payloads = self.manifest_store.page_payloads(
            split.table.table_key,
            m,
            {c: schema[c] for c in columns},
            split.row_start,
            split.row_end,
        )
        return payloads

    def lake_schema(
        self, handle: TableHandle
    ) -> Optional[Dict[str, T.DataType]]:
        m = self.lake_manifest(handle)
        return m.engine_schema() if m is not None else None

    def lake_table_stats(
        self, handle: TableHandle
    ) -> Optional[TableStats]:
        """Stats straight from the pinned manifest (zero file reads):
        row count plus per-column min/max aggregated over the file
        list — the same optimizer inputs the parquet footer provides."""
        m = self.lake_manifest(handle)
        if m is None:
            return None
        mins: Dict[str, float] = {}
        maxs: Dict[str, float] = {}
        for f in m.files:
            for c, (lo, hi) in f.minmax:
                mins[c] = lo if c not in mins else min(mins[c], lo)
                maxs[c] = hi if c not in maxs else max(maxs[c], hi)
        cols = {
            c: ColumnStats(
                min_value=float(mins[c]), max_value=float(maxs[c])
            )
            for c in mins
        }
        return TableStats(row_count=float(m.row_count), columns=cols)

    def lake_list_schemas(self) -> List[str]:
        store = self.manifest_store
        if store is None:
            return []
        return sorted(
            {tk[1] for tk in store.tables() if self._lake_owns(tk)}
        )

    def lake_list_tables(self, schema: str) -> List[str]:
        store = self.manifest_store
        if store is None:
            return []
        return sorted(
            tk[2]
            for tk in store.tables()
            if tk[1] == schema and self._lake_owns(tk)
        )
