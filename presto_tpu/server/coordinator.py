"""Coordinator process: SQL frontend, discovery, stage scheduling,
exchange client, paged client protocol.

Reference parity: the coordinator half of SURVEY.md §1/§3 —
``POST /v1/statement`` with paged ``nextUri`` results (L0),
parse/plan/fragment (L1-L2), stage scheduling to workers over the task
protocol (L3), the consumer side of the paged exchange
(``ExchangeClient``), embedded discovery with TTL-expiring worker
announcements and failure detection (SURVEY.md §5.3).

Round-1 multihost shape documented in server.scheduler.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import threading
import time
import traceback
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.exec.staging import stage_page
from presto_tpu.plan import nodes as N
from presto_tpu.server import pages_wire
from presto_tpu.server.protocol import FragmentSpec
from presto_tpu.server.scheduler import assign_ranges, plan_stage
from presto_tpu.utils.metrics import REGISTRY

#: announcement TTL: a worker silent this long is dropped (reference:
#: discovery TTL expiry removing dead nodes from scheduling)
NODE_TTL_S = 10.0
RESULT_PAGE_ROWS = 4096


@dataclasses.dataclass
class _WorkerNode:
    node_id: str
    uri: str
    last_seen: float
    version: str = "presto-tpu-0.1"
    coordinator: bool = False
    state: str = "ACTIVE"


class _Query:
    def __init__(self, qid: str, sql: str):
        self.qid = qid
        self.sql = sql
        self.state = "QUEUED"
        self.error: Optional[str] = None
        self.columns: List[dict] = []
        self.rows: List[list] = []
        self.done = threading.Event()


class CoordinatorServer:
    """Coordinator: embedded discovery + dispatcher + exchange client.

    Admission control (reference: DispatchManager + resource-group
    queueing, SURVEY.md §2.1 "Dispatch/queue"): at most
    ``max_concurrent_queries`` run at once; up to ``max_queued_queries``
    wait; beyond that submissions are REJECTED immediately instead of
    accumulating unbounded threads."""

    def __init__(
        self,
        port: int = 0,
        catalogs=None,
        session=None,
        max_concurrent_queries: int = 4,
        max_queued_queries: int = 100,
    ):
        from presto_tpu.exec.local_runner import LocalQueryRunner

        self.local = LocalQueryRunner(catalogs=catalogs, session=session)
        self.local.cluster = self  # system.runtime.nodes source
        self.workers: Dict[str, _WorkerNode] = {}
        self.queries: Dict[str, _Query] = {}
        self._lock = threading.Lock()
        self._qid = itertools.count(1)
        self._shutting_down = False
        self._admit = threading.Semaphore(max_concurrent_queries)
        self._max_queued = max_queued_queries
        self._pending = 0  # queued + running, admission-gated

        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self.uri = f"http://127.0.0.1:{self.port}"
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    def start(self) -> "CoordinatorServer":
        self._serve_thread.start()
        return self

    def shutdown(self) -> None:
        self._shutting_down = True
        # httpd.shutdown() handshakes with the serve_forever loop and
        # blocks forever if that loop never ran (server constructed but
        # not .start()ed, e.g. in-process submit()-only tests).
        if self._serve_thread.is_alive():
            self.httpd.shutdown()
        self.httpd.server_close()

    # ---------------------------------------------------------- discovery

    def announce(self, node_id: str, uri: str) -> None:
        with self._lock:
            w = self.workers.get(node_id)
            if w is None:
                self.workers[node_id] = _WorkerNode(
                    node_id=node_id, uri=uri, last_seen=time.time()
                )
            else:
                w.last_seen = time.time()
                w.uri = uri

    def active_workers(self) -> List[_WorkerNode]:
        now = time.time()
        with self._lock:
            return [
                w
                for w in self.workers.values()
                if now - w.last_seen <= NODE_TTL_S
            ]

    def nodes(self) -> List[_WorkerNode]:
        """All nodes incl. self, for system.runtime.nodes."""
        me = _WorkerNode(
            node_id="coordinator",
            uri=self.uri,
            last_seen=time.time(),
            coordinator=True,
        )
        now = time.time()
        with self._lock:
            others = [
                dataclasses.replace(
                    w,
                    state=(
                        "ACTIVE"
                        if now - w.last_seen <= NODE_TTL_S
                        else "GONE"
                    ),
                )
                for w in self.workers.values()
            ]
        return [me] + others

    # ------------------------------------------------------------ queries

    def submit(self, sql: str) -> _Query:
        q = _Query(f"q_{next(self._qid)}", sql)
        with self._lock:
            self.queries[q.qid] = q
            if self._pending >= self._max_queued:
                q.state = "FAILED"
                q.error = (
                    "Query rejected: too many queued queries "
                    f"(max {self._max_queued})"
                )
                REGISTRY.counter("coordinator.queries_rejected").update()
                q.done.set()
                return q
            self._pending += 1
        threading.Thread(
            target=self._execute_query, args=(q,), daemon=True
        ).start()
        return q

    def _execute_query(self, q: _Query) -> None:
        with self._admit:  # admission gate: bounded concurrency
            q.state = "RUNNING"
            try:
                with REGISTRY.timer("coordinator.query_time").time():
                    self._run_sql(q)
                q.state = "FINISHED"
            except Exception as e:
                q.state = "FAILED"
                q.error = (
                    f"{type(e).__name__}: {e}\n"
                    f"{traceback.format_exc()[-1000:]}"
                )
                REGISTRY.counter("coordinator.queries_failed").update()
            finally:
                with self._lock:
                    self._pending -= 1
                q.done.set()

    def _run_sql(self, q: _Query) -> None:
        from presto_tpu.exec.host_ops import apply_host_ops, peel_host_ops
        from presto_tpu.parallel.fragmenter import insert_gathers
        from presto_tpu.plan.optimizer import prune_columns
        from presto_tpu.plan.planner import plan_statement
        from presto_tpu.sql import ast, parse_statement

        stmt = parse_statement(q.sql)
        workers = self.active_workers()
        if not isinstance(stmt, ast.Select) or not workers:
            # non-SELECT (SET SESSION / SHOW / EXPLAIN) or empty cluster:
            # run on the coordinator's local engine
            res = self.local.execute(q.sql)
            self._store_result(q, res)
            return

        plan = plan_statement(stmt, self.local.catalogs, self.local.session)
        root = prune_columns(self.local._bind_params(plan))
        host_ops: List[N.PlanNode] = []
        if self.local.session.get("host_root_stage"):
            root, host_ops = peel_host_ops(root)
        froot = insert_gathers(root)
        remotes = [
            n for n in N.walk(froot) if isinstance(n, N.RemoteSourceNode)
        ]
        if not remotes:
            res = self.local.execute_plan(plan)
            self._store_result(q, res)
            return
        pages = [
            self._run_stage(r.fragment_root, workers, q) for r in remotes
        ]
        page = self.local._run_with_pages(froot, remotes, pages)
        if host_ops:
            page = apply_host_ops(page, host_ops)
        from presto_tpu.exec.local_runner import QueryResult

        self._store_result(q, QueryResult(plan.output_names, page))

    # ------------------------------------------------------- stage runner

    def _run_stage(self, fragment_root, workers, q: _Query):
        """Schedule one fragment across workers; gather + finalize."""
        stage = plan_stage(fragment_root, self.local.catalogs)
        if stage is None:
            # no scan admits a semantics-preserving partitioning:
            # single-task fallback on the coordinator's local engine
            return self.local._run(fragment_root)
        ranges = assign_ranges(stage.partition_rows, len(workers))
        specs = []
        for w, (lo, hi) in zip(workers, ranges):
            specs.append(
                (
                    w,
                    FragmentSpec(
                        task_id=f"{q.qid}.{uuid.uuid4().hex[:8]}",
                        query_id=q.qid,
                        fragment=stage.worker_fragment,
                        partition_scan=stage.partition_scan,
                        split_start=lo,
                        split_end=hi,
                        split_batch_rows=int(
                            self.local.session.get("page_capacity")
                        ),
                        task_concurrency=int(
                            self.local.session.get("task_concurrency")
                        ),
                    ),
                )
            )
        for w, spec in specs:
            self._http_json(
                "POST", w.uri + "/v1/task", spec.to_json()
            )
        payloads = []
        for w, spec in specs:
            payloads.extend(self._pull_task(w, spec))
        # delete tasks (ack) regardless of outcome
        for w, spec in specs:
            try:
                self._http_json(
                    "DELETE", f"{w.uri}/v1/task/{spec.task_id}", None
                )
            except Exception:
                pass

        remote = [
            n
            for n in N.walk(stage.final_root)
            if isinstance(n, N.RemoteSourceNode)
        ]
        schema = dict(stage.worker_fragment.output_schema())
        merged = pages_wire.merge_payloads(payloads, schema)
        page = stage_page(merged, schema)
        # the final plan may contain real scans above the cut (e.g. a
        # join against another table after the final aggregation) —
        # load those locally alongside the gathered remote page
        local_scans = [
            n
            for n in N.walk(stage.final_root)
            if isinstance(n, N.TableScanNode)
        ]
        leaves = remote + local_scans
        pages = [page] + [self.local._load_table(s) for s in local_scans]
        return self.local._run_with_pages(stage.final_root, leaves, pages)

    def _pull_task(self, w, spec) -> List[tuple]:
        """Token-acked page pulls until X-Complete (exchange client)."""
        token = 0
        out = []
        deadline = time.time() + float(
            self.local.session.get("query_max_run_time_s")
        )
        while True:
            if time.time() > deadline:
                raise TimeoutError(f"task {spec.task_id} timed out")
            url = f"{w.uri}/v1/task/{spec.task_id}/results/0/{token}"
            req = urllib.request.Request(url)
            with urllib.request.urlopen(req, timeout=30) as resp:
                complete = resp.headers.get("X-Complete") == "true"
                nxt = int(resp.headers.get("X-Next-Token", token))
                if resp.status == 200:
                    out.append(pages_wire.deserialize_page(resp.read()))
                if complete and nxt == token + (
                    1 if resp.status == 200 else 0
                ):
                    return out
                if nxt == token and resp.status != 200:
                    # no page yet: check for failure, then poll again
                    st = self._http_json(
                        "GET",
                        f"{w.uri}/v1/task/{spec.task_id}/status",
                        None,
                    )
                    if st.get("state") == "FAILED":
                        raise RuntimeError(
                            f"task on {w.node_id} failed: {st.get('error')}"
                        )
                    time.sleep(0.05)
                token = nxt

    # ------------------------------------------------------------ helpers

    def _http_json(self, method: str, url: str, body) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url,
            data=data,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            raw = resp.read()
        return json.loads(raw) if raw else {}

    def _store_result(self, q: _Query, res) -> None:
        q.columns = [
            {"name": c} for c in res.columns
        ]
        q.rows = [list(r) for r in res.rows()]


def _make_handler(coord: CoordinatorServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _json(self, code: int, obj) -> None:
            # default=str: result rows may carry dates/decimals; the
            # oracle-compatible wire form is their string rendering
            body = json.dumps(obj, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_body(self) -> bytes:
            n = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(n)

        def do_POST(self):
            parts = [p for p in self.path.split("/") if p]
            if parts == ["v1", "statement"]:
                sql = self._read_body().decode()
                q = coord.submit(sql)
                return self._json(
                    200,
                    {
                        "id": q.qid,
                        "nextUri": f"{coord.uri}/v1/statement/{q.qid}/0",
                    },
                )
            self._json(404, {"error": f"no route {self.path}"})

        def do_PUT(self):
            parts = [p for p in self.path.split("/") if p]
            if parts == ["v1", "announcement"]:
                d = json.loads(self._read_body().decode())
                coord.announce(d["node_id"], d["uri"])
                return self._json(200, {"ok": True})
            self._json(404, {"error": f"no route {self.path}"})

        def do_GET(self):
            parts = [p for p in self.path.split("/") if p]
            if parts == ["v1", "cluster"]:
                return self._json(
                    200,
                    {
                        "workers": [
                            {"node_id": w.node_id, "uri": w.uri}
                            for w in coord.active_workers()
                        ]
                    },
                )
            if parts == ["v1", "metrics"]:
                body = REGISTRY.render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if len(parts) == 4 and parts[:2] == ["v1", "statement"]:
                qid, token = parts[2], int(parts[3])
                q = coord.queries.get(qid)
                if q is None:
                    return self._json(404, {"error": "no such query"})
                # long-poll up to 1s for progress (reference: long-poll)
                q.done.wait(timeout=1.0)
                if q.state == "FAILED":
                    return self._json(
                        200,
                        {
                            "id": qid,
                            "error": q.error,
                            "stats": {"state": "FAILED"},
                        },
                    )
                if not q.done.is_set():
                    return self._json(
                        200,
                        {
                            "id": qid,
                            "stats": {"state": q.state},
                            "nextUri": (
                                f"{coord.uri}/v1/statement/{qid}/{token}"
                            ),
                        },
                    )
                lo = token * RESULT_PAGE_ROWS
                hi = min(lo + RESULT_PAGE_ROWS, len(q.rows))
                out = {
                    "id": qid,
                    "columns": q.columns,
                    "data": q.rows[lo:hi],
                    "stats": {"state": "FINISHED"},
                }
                if hi < len(q.rows):
                    out["nextUri"] = (
                        f"{coord.uri}/v1/statement/{qid}/{token + 1}"
                    )
                return self._json(200, out)
            self._json(404, {"error": f"no route {self.path}"})

    return Handler
